"""Llama-family decoder, TPU-first.

The reference framework does not ship models (it wraps user torch modules +
transformers' ``tp_plan``, reference: accelerator.py:1580-1656); a TPU-native
framework must own the TP rule tables and the flagship architecture used by
its benchmarks (BASELINE.json: FSDP2 Llama-7B tokens/sec/chip). Design points:

- **MXU-shaped**: all projections are single large matmuls in bf16; head dim
  128 (= MXU lane width); no per-head Python loops.
- **scan over layers**: identical blocks rolled into one ``nn.scan`` — one
  trace/compile of the block instead of L (the analog of the reference's
  "regional compilation", utils/other.py:106-177, its 5-9× compile win).
- **remat**: optional ``nn.remat`` on the block to trade FLOPs for HBM.
- **TP rules**: Megatron-style column/row parallel table as name-regex →
  PartitionSpec over the ``tp`` mesh axis; composes with FSDP sharding of the
  remaining dim (parallel/sharding.py).
- **attention seam**: the inner attention call dispatches on the active mesh
  (cp → ring attention, sp → Ulysses all-to-all, else flash/native) so the
  same module serves all sequence-parallel modes.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(unsafe_hash=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    attention_bias: bool = False        # Qwen2-style checkpoints: bias on q/k/v
    # Decoder-variant knobs (all default off → plain Llama). These make the
    # family a configurable decoder chassis: most Llama-era architectures
    # (StarCoder2, StableLM, InternLM2, Granite, ...) are this block with
    # different constants, which is what lets models/generic_hub.py ingest
    # unseen checkpoints with declarative rules instead of new module code.
    norm_type: str = "rmsnorm"          # "layernorm": mean-centered, with bias
    mlp_gated: bool = True              # False: up_proj -> act -> down_proj
    mlp_bias: bool = False              # biases on the MLP projections
    attention_out_bias: bool = False    # bias on o_proj
    partial_rotary_factor: float = 1.0  # rotate only this fraction of head_dim
    # Granite-style scaling constants (all 1.0 → plain Llama). The attention
    # multiplier replaces the 1/sqrt(head_dim) score scale; it is folded into
    # the q projection output (q *= mult*sqrt(d)) so every attention impl —
    # the Pallas kernel included — runs unchanged.
    embedding_multiplier: float = 1.0
    residual_multiplier: float = 1.0
    attention_multiplier: Optional[float] = None
    logits_scaling: float = 1.0
    # Gemma-family quirks (all default off → plain Llama):
    hidden_act: str = "silu"            # "gelu_tanh" for Gemma's GeGLU
    rms_norm_plus_one: bool = False     # norm scale stored as (weight + 1)
    scale_embeddings: bool = False      # multiply embeddings by sqrt(hidden)
    dtype: Any = jnp.bfloat16          # compute dtype (params stay fp32 masters)
    scan_layers: bool = True
    remat: bool = False
    # What the block remat saves (only meaningful with remat=True):
    #   flash   — keep the flash kernel's O(S) residuals, recompute the rest
    #   dots    — additionally keep every matmul output (recompute only
    #             elementwise ops; more HBM, fewer recomputed FLOPs)
    #   minimal — recompute everything, flash kernel included
    remat_policy: str = "flash"
    # flash = Pallas fused kernel on TPU (blockwise scan fallback off-TPU);
    # native = materialized O(S²) softmax, kept for parity tests.
    attention_impl: str = "flash"       # flash | native | ring | ulysses
    fp8: bool = False                   # fp8 matmuls in MLP/attention projections
    fp8_format: str = "HYBRID"          # E4M3 | E5M2 | HYBRID (e4m3 fwd / e5m2 bwd)
    fp8_backend: str = "AUTO"           # AUTO | TE | AO | QDQ (ops/fp8.py backend_to_native)

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if self.norm_type not in ("rmsnorm", "layernorm"):
            raise ValueError(f"norm_type must be rmsnorm|layernorm, got {self.norm_type}")
        if self.rotary_dim % 2:
            raise ValueError(
                f"partial_rotary_factor {self.partial_rotary_factor} of head_dim "
                f"{self.head_dim} gives odd rotary_dim {self.rotary_dim}"
            )

    @property
    def rotary_dim(self) -> int:
        return int(self.head_dim * self.partial_rotary_factor)

    @property
    def dot_general(self):
        """dot_general injected into every projection: fp8 when enabled
        (ops/fp8.py — the reference's TE/AO fp8 linear swap role), else the
        XLA default."""
        if not self.fp8:
            return None
        from ..ops.fp8 import backend_to_native, fp8_dot_general

        return fp8_dot_general(
            self.fp8_format, native=backend_to_native(self.fp8_backend)
        )

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256, hidden_size=128, intermediate_size=384,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def llama_7b(cls, **kw):
        return cls(**kw)

    @classmethod
    def llama_1b(cls, **kw):
        return cls(
            hidden_size=2048, intermediate_size=5504, num_hidden_layers=16,
            num_attention_heads=16, num_key_value_heads=16, **kw,
        )


def rms_norm(x, weight, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def layer_norm(x, weight, bias, eps):
    """Functional mean-centered norm — the single source of the numerics
    shared by the LayerNorm module (training) and generation's KV-cache
    decode plan (parity depends on them staying bit-identical)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * weight + bias).astype(x.dtype)


def scale_residual(y, mult: float):
    """Branch residual scaling (Granite residual_multiplier) — single source
    for the training module and generation's decode plan."""
    return y if mult == 1.0 else y * jnp.asarray(mult, y.dtype)


def apply_partial_rope(x, cos, sin, rotary_dim):
    """RoPE on the leading ``rotary_dim`` dims, pass-through on the rest
    (StableLM/NeoX-style); shared by LlamaAttention and the decode plan."""
    d = x.shape[-1]
    if rotary_dim == d:
        return apply_rope(x, cos, sin)
    return jnp.concatenate(
        [apply_rope(x[..., :rotary_dim], cos, sin), x[..., rotary_dim:]], -1
    )


class RMSNorm(nn.Module):
    eps: float = 1e-5
    plus_one: bool = False  # Gemma stores scale as (weight + 1), init zeros

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.zeros if self.plus_one else nn.initializers.ones
        weight = self.param("weight", init, (x.shape[-1],), jnp.float32)
        if self.plus_one:
            weight = weight + 1.0
        return rms_norm(x, weight.astype(x.dtype), self.eps)


class LayerNorm(nn.Module):
    """Mean-centered norm with bias, params named weight/bias to match the
    torch checkpoint convention the hub mappings use (flax's nn.LayerNorm
    calls them scale/bias)."""

    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        weight = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],), jnp.float32)
        return layer_norm(x, weight, bias, self.eps)


def make_norm(cfg: "LlamaConfig", name: str):
    if cfg.norm_type == "layernorm":
        return LayerNorm(cfg.rms_norm_eps, name=name)
    return RMSNorm(cfg.rms_norm_eps, cfg.rms_norm_plus_one, name=name)


def activation_fn(name: str):
    table = {
        "silu": nn.silu,
        "gelu": partial(nn.gelu, approximate=False),
        "gelu_tanh": partial(nn.gelu, approximate=True),
        "gelu_new": partial(nn.gelu, approximate=True),
        "gelu_pytorch_tanh": partial(nn.gelu, approximate=True),
        "relu": nn.relu,
    }
    if name not in table:
        raise ValueError(f"Unknown hidden_act {name!r}; known: {sorted(table)}")
    return table[name]


def rotary_embedding(positions: jax.Array, head_dim: int, theta: float, dtype) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for RoPE, computed on the fly (cheap, fuses)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions[..., None].astype(jnp.float32) * inv_freq  # (..., S, D/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, S, H, D); cos/sin: (B, S, D) or (S, D)."""
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


def naive_attention(q, k, v, *, causal: bool = True, segment_positions=None):
    """Reference attention in pure jnp — correct under GSPMD for dp/tp/fsdp.
    q: (B, S, Hq, D); k/v: (B, S, Hkv, D). GQA via head repetition (XLA turns
    the broadcast into an efficient layout, no materialized copy)."""
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _dispatch_attention(impl: str):
    if impl in ("native",):
        return naive_attention
    if impl == "flash":
        from ..ops.flash_attention import auto_flash_attention

        return auto_flash_attention
    if impl == "ring":
        from ..parallel.cp import ring_attention

        return ring_attention
    if impl == "ulysses":
        from ..parallel.sp import ulysses_attention

        return ulysses_attention
    raise ValueError(f"Unknown attention_impl {impl}")


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        d = cfg.head_dim
        dense = partial(
            nn.DenseGeneral, use_bias=cfg.attention_bias, dtype=cfg.dtype,
            param_dtype=jnp.float32,
            **({"dot_general": cfg.dot_general} if cfg.fp8 else {}),
        )
        q = dense(features=(cfg.num_attention_heads, d), name="q_proj")(x)
        k = dense(features=(cfg.num_key_value_heads, d), name="k_proj")(x)
        v = dense(features=(cfg.num_key_value_heads, d), name="v_proj")(x)
        if cfg.attention_multiplier is not None:
            # Exact: attn computes (q*c*sqrt(d)) . k / sqrt(d) = c * (q.k).
            q = q * jnp.asarray(
                cfg.attention_multiplier * np.sqrt(d), q.dtype
            )
        rd = cfg.rotary_dim
        cos, sin = rotary_embedding(positions, rd, cfg.rope_theta, x.dtype)
        q = apply_partial_rope(q, cos, sin, rd)
        k = apply_partial_rope(k, cos, sin, rd)
        attn_fn = _dispatch_attention(cfg.attention_impl)
        out = attn_fn(q, k, v, causal=True)
        return nn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1), use_bias=cfg.attention_out_bias,
            dtype=cfg.dtype, param_dtype=jnp.float32, name="o_proj",
            **({"dot_general": cfg.dot_general} if cfg.fp8 else {}),
        )(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = partial(
            nn.Dense, use_bias=cfg.mlp_bias, dtype=cfg.dtype, param_dtype=jnp.float32,
            **({"dot_general": cfg.dot_general} if cfg.fp8 else {}),
        )
        act = activation_fn(cfg.hidden_act)
        up = dense(cfg.intermediate_size, name="up_proj")(x)
        if cfg.mlp_gated:
            gate = dense(cfg.intermediate_size, name="gate_proj")(x)
            hidden = act(gate) * up
        else:  # plain 2-layer MLP (GPT/StarCoder2-style)
            hidden = act(up)
        return dense(cfg.hidden_size, name="down_proj")(hidden)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        rm = cfg.residual_multiplier
        h = x + scale_residual(
            LlamaAttention(cfg, name="self_attn")(
                make_norm(cfg, "input_layernorm")(x), positions
            ),
            rm,
        )
        return h + scale_residual(
            LlamaMLP(cfg, name="mlp")(
                make_norm(cfg, "post_attention_layernorm")(h)
            ),
            rm,
        )


class _ScannedBlock(nn.Module):
    """LlamaBlock wrapped for nn.scan: carry = hidden states."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = LlamaBlock(self.config, name="block")(x, positions)
        return (x, positions), None


class LlamaModel(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="embed_tokens",
        )(input_ids)
        if cfg.scale_embeddings:  # Gemma normalizer
            x = x * jnp.asarray(np.sqrt(cfg.hidden_size), cfg.dtype)
        if cfg.embedding_multiplier != 1.0:  # Granite scaling
            x = x * jnp.asarray(cfg.embedding_multiplier, cfg.dtype)
        positions = jnp.arange(input_ids.shape[-1])[None, :].astype(jnp.int32)
        positions = jnp.broadcast_to(positions, input_ids.shape)
        # Selective remat: with the flash kernel the attention residuals
        # (out, lse) are O(S), so save exactly those and recompute the rest —
        # the backward reuses the kernel outputs instead of re-running the
        # forward kernel. (With native attention there is nothing cheap to
        # save; plain full-block remat applies.)
        remat_kwargs = {"prevent_cse": False}
        policy = cfg.remat_policy
        if os.environ.get("ACCELERATE_FLASH_REMAT_POLICY", "1") == "0":
            policy = "minimal"  # legacy escape hatch
        if cfg.remat and policy != "minimal":
            save_flash = jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"
            )
            if policy == "dots":
                remat_kwargs["policy"] = jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable, save_flash
                )
            elif cfg.attention_impl != "native":
                remat_kwargs["policy"] = save_flash
        if cfg.scan_layers:
            block = _ScannedBlock
            if cfg.remat:
                block = nn.remat(block, **remat_kwargs)
            scanned = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            (x, _), _ = scanned((x, positions), None)
        else:
            for i in range(cfg.num_hidden_layers):
                blk = LlamaBlock
                if cfg.remat:
                    blk = nn.remat(blk, **remat_kwargs)
                x = blk(cfg, name=f"layers_{i}")(x, positions)
        return make_norm(cfg, "norm")(x)


class LlamaForCausalLM(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        x = LlamaModel(cfg, name="model")(input_ids)
        x = _pin_last_dim_replicated(x)  # see helper: kills FSDP param-sharding
        if cfg.tie_word_embeddings:     # propagation into the loss graph
            embed = self.variables["params"]["model"]["embed_tokens"]["embedding"]
            logits = x @ embed.T.astype(cfg.dtype)
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
                name="lm_head",
            )(x)
        if cfg.logits_scaling != 1.0:  # Granite: logits / scaling
            logits = logits / jnp.asarray(cfg.logits_scaling, logits.dtype)
        return logits



# ---------------------------------------------------------------------------
# Tensor-parallel rule table (the role of transformers' tp_plan, owned
# in-framework per SURVEY.md §7 hard-part 3). Regexes match "/"-joined param
# paths; specs are dim-aligned with the param shapes. With scan_layers the
# block params gain a leading layer dim, hence the leading None.
# ---------------------------------------------------------------------------

def llama_tp_rules(scan_layers: bool = True) -> list[tuple[str, tuple]]:
    lead = (None,) if scan_layers else ()
    rules = [
        # Column-parallel: shard heads/ffn (output) dim.
        (r"self_attn/(q_proj|k_proj|v_proj)/kernel", lead + (None, "tp", None)),
        (r"mlp/(gate_proj|up_proj)/kernel", lead + (None, "tp")),
        # Row-parallel: shard input dim; XLA inserts the psum on the output.
        (r"self_attn/o_proj/kernel", lead + ("tp", None, None)),
        (r"mlp/down_proj/kernel", lead + ("tp", None)),
        # Embedding + head sharded on vocab.
        (r"embed_tokens/embedding", ("tp", None)),
        (r"lm_head/kernel", (None, "tp")),
    ]
    return [(pat, P(*spec) if isinstance(spec, tuple) else spec) for pat, spec in rules]


def fused_cross_entropy_loss(config, params, input_ids, labels,
                             ignore_index: int = -100, chunk_size: int = 256):
    """Causal-LM loss with the head matmul folded into a chunked loss.

    The naive path materializes (B, S, V) logits and log-softmaxes them in
    fp32 — for a 32k vocab at seq 2048 that's gigabytes of HBM traffic per
    step, pure bandwidth with no MXU work. Here the sequence is scanned in
    ``chunk_size`` slices: each slice's logits live only inside the scan body
    (rematerialized in the backward), and the loss needs just the slice's
    log-sum-exp and the label logit. Exactly equal to
    ``cross_entropy_loss(module.apply(...), labels)`` up to fp32 summation
    order.

    ``params`` is the full LlamaForCausalLM tree (``model`` + optional
    ``lm_head``).
    """
    cfg = config
    hidden = LlamaModel(cfg, name="model").apply({"params": params["model"]}, input_ids)
    # Same FSDP/HSDP propagation fix as LlamaForCausalLM.__call__ /
    # cross_entropy_loss: without these pins the sharded head param leaks
    # vocab/hidden sharding into the scan-local loss graph and the backward
    # pays an involuntary full rematerialization (see _pin_last_dim_replicated).
    hidden = _pin_last_dim_replicated(hidden)
    if cfg.tie_word_embeddings:
        head = params["model"]["embed_tokens"]["embedding"].T
    else:
        head = params["lm_head"]["kernel"]
    head = head.astype(cfg.dtype)  # (H, V)

    b, s, h = hidden.shape
    n_chunks = max(1, s // chunk_size)
    if s % chunk_size:
        n_chunks, chunk_size = 1, s  # odd tails: fall back to one chunk
    hc = hidden.reshape(b, n_chunks, chunk_size, h).transpose(1, 0, 2, 3)
    yc = labels.reshape(b, n_chunks, chunk_size).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_loss(carry, xs):
        hx, y = xs
        logits = _pin_last_dim_replicated((hx @ head).astype(jnp.float32))
        valid = y != ignore_index
        safe = jnp.where(valid, y, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        token_loss = jnp.where(valid, lse - picked, 0.0)
        loss_sum, count = carry
        return (loss_sum + token_loss.sum(), count + valid.sum()), None

    (loss_sum, count), _ = jax.lax.scan(chunk_loss, (0.0, 0), (hc, yc))
    return loss_sum / jnp.maximum(count, 1)


def _pin_last_dim_replicated(x):
    """Constrain ``x``'s last dim to replicated; other dims stay
    UNCONSTRAINED (free for batch/seq propagation).

    Applied at the two activation boundaries around the unembed matmul
    (final hidden and logits). Under FSDP/HSDP every param — including 1-D
    norm scales and the lm_head kernel — is sharded over ``dp_shard``, and
    shardy propagates those param shardings into the activations (hidden /
    vocab dim sharded), while the label-scatter path of the CE backward
    stays batch-sharded. The mismatched cotangents meet in an ``add_any``
    that GSPMD can only reconcile by involuntary full rematerialization
    (replicate + repartition — the ``[SPMD]`` compile warning; wasted HBM +
    ICI every step). Pinning just the feature dim keeps the loss graph
    batch-sharded; sharded params are all-gathered at use like any other
    FSDP weight. (Block outputs are feature-replicated under Megatron-style
    TP too, so this is sharding-neutral for TP/CP/SP.) Passive singleton
    peek (no AcceleratorState construction) for the same reason as
    parallel/pp.py:_resolve_virtual_stages."""
    from ..state import AcceleratorState

    mesh = AcceleratorState._shared_state.get("_mesh")
    if mesh is None or getattr(x, "ndim", 0) < 2:
        return x
    try:
        from jax.sharding import AxisType, get_abstract_mesh

        ambient = get_abstract_mesh()
        manual = ambient is not None and any(
            t == AxisType.Manual for t in ambient.axis_types
        )
    except ImportError:
        # jax < 0.5 has no AxisType/get_abstract_mesh; inside shard_map the
        # mesh axes are bound in the named-axis env instead.
        from jax._src import core as _core

        manual = bool(_core.nonempty_axis_env())
    if manual:
        # Inside shard_map (manual axes) — e.g. a comm-hook step or the
        # GPipe stage body — sharding constraints don't apply (and raise);
        # the caller already controls the layout by hand.
        return x
    if mesh.shape.get("pp", 1) > 1:
        # Under GPipe the last stage computes the unembed inside shard_map
        # with its own stage-local layout; pinning the collected logits on
        # the global mesh would force a conflicting reshard in the backward
        # ppermute chain (observed as a fresh [SPMD] remat warning).
        return x
    if mesh.shape.get("tp", 1) > 1:
        # Megatron-style vocab-parallel TP (llama_tp_rules shards
        # lm_head/kernel and the embedding on tp) deliberately keeps the
        # vocab dim of logits tp-sharded; forcing replication here would
        # all-gather the full fp32 (B,S,V) logits every step.
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(*([P.UNCONSTRAINED] * (x.ndim - 1)), None)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Token-level CE with masking — computed in fp32 regardless of compute
    dtype (loss reductions always fp32 on TPU to avoid bf16 accumulation
    error)."""
    logits = _pin_last_dim_replicated(logits).astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    token_loss = jnp.where(valid, token_loss, 0.0)
    return token_loss.sum() / jnp.maximum(valid.sum(), 1)
