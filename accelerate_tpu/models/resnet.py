"""ResNet-family image classifier, TPU-first.

The reference's CV story is torchvision's ResNet-50 driven by the example
scripts (reference: examples/cv_example.py — BASELINE.json names it as a
headline config); the framework itself never owns a CNN. A TPU-native build
does: this is a native flax ResNet v1.5 with the design points that matter on
TPU:

- **NHWC (channels-last)** throughout — the conv layout XLA:TPU tiles best.
- **bf16 compute, fp32 params/stats** via the same MixedPrecisionPolicy flow
  as the transformer families.
- **BatchNorm is sync-BN for free**: under GSPMD the batch axis is dp-sharded,
  so the batch-mean/variance reductions compile to cross-device collectives —
  what the reference needs `SyncBatchNorm.convert_sync_batchnorm` for.
  Running stats ride `Model.extra_state` / `TrainState.extra_state` and are
  updated by `prepare_train_step(..., mutable_state=True)`.
- Bottleneck blocks with the v1.5 stride placement (stride on the 3×3), zero-
  init of the last BN scale per block (the standard trick, helps early LR).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(unsafe_hash=True)
class ResNetConfig:
    num_classes: int = 1000
    width: int = 64
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(num_classes=4, width=16, stage_sizes=(1, 1))
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def resnet50(cls, **kw):
        return cls(**kw)

    @classmethod
    def resnet101(cls, **kw):
        return cls(stage_sizes=(3, 4, 23, 3), **kw)

    @classmethod
    def resnet152(cls, **kw):
        return cls(stage_sizes=(3, 8, 36, 3), **kw)


class BottleneckBlock(nn.Module):
    config: ResNetConfig
    filters: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=cfg.bn_momentum,
            epsilon=cfg.bn_eps, dtype=cfg.dtype, param_dtype=jnp.float32,
        )
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride), name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(4 * self.filters, (1, 1), name="conv3")(y)
        # Zero-init the block's last BN scale: the block starts as identity.
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(4 * self.filters, (1, 1),
                            strides=(self.stride, self.stride), name="downsample")(x)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Images (B, H, W, 3) → logits (B, num_classes) in fp32."""

    config: ResNetConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        cfg = self.config
        x = images.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=cfg.dtype, param_dtype=jnp.float32, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=cfg.bn_momentum,
                         epsilon=cfg.bn_eps, dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            filters = cfg.width * (2 ** stage)
            for block in range(n_blocks):
                stride = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(cfg, filters=filters, stride=stride,
                                    name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
                        name="classifier")(x)


def resnet_loss(module, params, batch_stats, images, labels, train: bool = True):
    """Cross-entropy loss threading BatchNorm stats — the shape
    ``prepare_train_step(mutable_state=True)`` expects.

    Returns ``(loss, new_extra_state)`` where extra_state is the flax
    variables dict ``{"batch_stats": ...}``.
    """
    import jax

    logits, mutated = module.apply(
        {"params": params, **(batch_stats or {})}, images, train=train,
        mutable=["batch_stats"],
    )
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1))
    return loss, dict(mutated)
