"""Hugging Face checkpoint interop: load reference-world weights natively.

The reference is a wrapper around user torch modules, so "model support" means
transformers checkpoints. For a reference user to switch here, the same
checkpoints must load into the native flax families — this module owns the
name/layout mapping (reference big-model load path for comparison:
utils/modeling.py:1805-2065 ``load_checkpoint_in_model``; here the mapping is
architectural, torch ``(out, in)`` linear layout → flax ``(in, out)`` kernels,
per-head reshapes for the fused DenseGeneral projections, layer stacking for
the ``nn.scan`` layout).

Two directions per family:

- ``*_params_from_hf(cfg, state_dict)`` — HF name→tensor dict (numpy or torch)
  → our param pytree, ready for ``Model(module=..., params=...)``.
- ``*_params_to_hf(cfg, params)`` — the inverse, for exporting checkpoints a
  reference/transformers user can load back.

``load_pretrained(src)`` is the high-level entry: src is a transformers model
instance, a local checkpoint directory (config.json + *.safetensors /
pytorch_model.bin), or a (config, state_dict) pair; the family is picked from
``model_type`` and both config and weights are converted.

Logit parity with transformers is asserted in tests/test_hub.py for every
family (fp32, tiny configs).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


def _np(t) -> np.ndarray:
    """Accept torch tensors / np arrays / anything array-like."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def _t(t) -> np.ndarray:
    return _np(t).T


def _set(tree: dict, path: str, value: np.ndarray) -> None:
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _get(tree: dict, path: str) -> np.ndarray:
    node = tree
    for p in path.split("/"):
        node = node[p]
    return np.asarray(node)


def _stack_layers(per_layer: list[dict]) -> dict:
    """[{path: arr} per layer] → {path: stacked arr} (the nn.scan layout)."""
    out = {}
    for key in per_layer[0]:
        out[key] = np.stack([layer[key] for layer in per_layer], axis=0)
    return out


def _place_layers(tree, stacked: dict, scan_layers: bool, scan_prefix: str,
                  unscanned_prefix_fmt: str, n_layers: int) -> None:
    if scan_layers:
        for path, arr in stacked.items():
            _set(tree, f"{scan_prefix}/{path}", arr)
    else:
        for path, arr in stacked.items():
            for i in range(n_layers):
                _set(tree, unscanned_prefix_fmt.format(i=i) + "/" + path, arr[i])


def _collect_layers(params, scan_layers: bool, scan_prefix: str,
                    unscanned_prefix_fmt: str, n_layers: int, paths: list[str]) -> list[dict]:
    """Inverse of _place_layers: per-layer dicts of {path: arr}."""
    layers = []
    for i in range(n_layers):
        layer = {}
        for path in paths:
            if scan_layers:
                layer[path] = _get(params, f"{scan_prefix}/{path}")[i]
            else:
                layer[path] = _get(params, unscanned_prefix_fmt.format(i=i) + "/" + path)
        layers.append(layer)
    return layers


# ---------------------------------------------------------------------------
# Llama
# ---------------------------------------------------------------------------

def llama_config_from_hf(hf: Any) -> "LlamaConfig":
    from .llama import LlamaConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return LlamaConfig(
        vocab_size=g("vocab_size"),
        hidden_size=g("hidden_size"),
        intermediate_size=g("intermediate_size"),
        num_hidden_layers=g("num_hidden_layers"),
        num_attention_heads=g("num_attention_heads"),
        num_key_value_heads=g("num_key_value_heads") or g("num_attention_heads"),
        head_dim=g("head_dim"),
        max_position_embeddings=g("max_position_embeddings", 4096),
        rms_norm_eps=g("rms_norm_eps", 1e-5),
        rope_theta=g("rope_theta", 10000.0),
        tie_word_embeddings=bool(g("tie_word_embeddings", False)),
        # Qwen2 always carries q/k/v biases; Llama/Mistral expose the flag.
        attention_bias=bool(
            g("attention_bias", g("model_type") == "qwen2")
        ),
    )


def llama_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, nkv, d = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    tree: dict = {"model": {}}
    _set(tree, "model/embed_tokens/embedding", _np(sd["model.embed_tokens.weight"]))
    _set(tree, "model/norm/weight", _np(sd["model.norm.weight"]))
    if not cfg.tie_word_embeddings:
        _set(tree, "lm_head/kernel", _t(sd["lm_head.weight"]))
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        layers.append({
            "self_attn/q_proj/kernel": _t(sd[p + "self_attn.q_proj.weight"]).reshape(h, nh, d),
            "self_attn/k_proj/kernel": _t(sd[p + "self_attn.k_proj.weight"]).reshape(h, nkv, d),
            "self_attn/v_proj/kernel": _t(sd[p + "self_attn.v_proj.weight"]).reshape(h, nkv, d),
            "self_attn/o_proj/kernel": _t(sd[p + "self_attn.o_proj.weight"]).reshape(nh, d, h),
            "mlp/gate_proj/kernel": _t(sd[p + "mlp.gate_proj.weight"]),
            "mlp/up_proj/kernel": _t(sd[p + "mlp.up_proj.weight"]),
            "mlp/down_proj/kernel": _t(sd[p + "mlp.down_proj.weight"]),
            "input_layernorm/weight": _np(sd[p + "input_layernorm.weight"]),
            "post_attention_layernorm/weight": _np(sd[p + "post_attention_layernorm.weight"]),
            **({
                "self_attn/q_proj/bias": _np(sd[p + "self_attn.q_proj.bias"]).reshape(nh, d),
                "self_attn/k_proj/bias": _np(sd[p + "self_attn.k_proj.bias"]).reshape(nkv, d),
                "self_attn/v_proj/bias": _np(sd[p + "self_attn.v_proj.bias"]).reshape(nkv, d),
            } if cfg.attention_bias else {}),
        })
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "model/layers/block", "model/layers_{i}", cfg.num_hidden_layers)
    return tree


def llama_params_to_hf(cfg, params) -> dict:
    h, nh, nkv, d = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    sd = {
        "model.embed_tokens.weight": _get(params, "model/embed_tokens/embedding"),
        "model.norm.weight": _get(params, "model/norm/weight"),
    }
    if not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = _get(params, "lm_head/kernel").T
    paths = [
        "self_attn/q_proj/kernel", "self_attn/k_proj/kernel", "self_attn/v_proj/kernel",
        "self_attn/o_proj/kernel", "mlp/gate_proj/kernel", "mlp/up_proj/kernel",
        "mlp/down_proj/kernel", "input_layernorm/weight", "post_attention_layernorm/weight",
    ]
    for i, layer in enumerate(_collect_layers(
        params, cfg.scan_layers, "model/layers/block", "model/layers_{i}",
        cfg.num_hidden_layers, paths,
    )):
        p = f"model.layers.{i}."
        sd[p + "self_attn.q_proj.weight"] = layer["self_attn/q_proj/kernel"].reshape(h, nh * d).T
        sd[p + "self_attn.k_proj.weight"] = layer["self_attn/k_proj/kernel"].reshape(h, nkv * d).T
        sd[p + "self_attn.v_proj.weight"] = layer["self_attn/v_proj/kernel"].reshape(h, nkv * d).T
        sd[p + "self_attn.o_proj.weight"] = layer["self_attn/o_proj/kernel"].reshape(nh * d, h).T
        sd[p + "mlp.gate_proj.weight"] = layer["mlp/gate_proj/kernel"].T
        sd[p + "mlp.up_proj.weight"] = layer["mlp/up_proj/kernel"].T
        sd[p + "mlp.down_proj.weight"] = layer["mlp/down_proj/kernel"].T
        sd[p + "input_layernorm.weight"] = layer["input_layernorm/weight"]
        sd[p + "post_attention_layernorm.weight"] = layer["post_attention_layernorm/weight"]
    return {k: np.asarray(v) for k, v in sd.items()}


def gemma_config_from_hf(hf: Any) -> "LlamaConfig":
    """Gemma rides the Llama family with three quirks: GeGLU MLP, RMSNorm
    scales stored as (weight + 1), embeddings scaled by sqrt(hidden)."""
    import dataclasses as _dc

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    cfg = llama_config_from_hf(hf)
    return _dc.replace(
        cfg,
        head_dim=g("head_dim", 256),
        tie_word_embeddings=True,
        hidden_act="gelu_tanh",
        rms_norm_plus_one=True,
        scale_embeddings=True,
    )


# ---------------------------------------------------------------------------
# Mixtral (Llama attention + sparse MoE MLP)
# ---------------------------------------------------------------------------

def mixtral_config_from_hf(hf: Any) -> "MixtralConfig":
    from .moe import MixtralConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return MixtralConfig(
        vocab_size=g("vocab_size"),
        hidden_size=g("hidden_size"),
        intermediate_size=g("intermediate_size"),
        num_hidden_layers=g("num_hidden_layers"),
        num_attention_heads=g("num_attention_heads"),
        num_key_value_heads=g("num_key_value_heads") or g("num_attention_heads"),
        max_position_embeddings=g("max_position_embeddings", 4096),
        rms_norm_eps=g("rms_norm_eps", 1e-5),
        rope_theta=g("rope_theta", 10000.0),
        num_local_experts=g("num_local_experts", 8),
        num_experts_per_tok=g("num_experts_per_tok", 2),
        router_aux_loss_coef=g("router_aux_loss_coef", 0.02),
    )


def mixtral_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, nkv, d = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    E = cfg.num_local_experts
    tree: dict = {"model": {}}
    _set(tree, "model/embed_tokens/embedding", _np(sd["model.embed_tokens.weight"]))
    _set(tree, "model/norm/weight", _np(sd["model.norm.weight"]))
    _set(tree, "lm_head/kernel", _t(sd["lm_head.weight"]))
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        m = p + "block_sparse_moe."
        layers.append({
            "self_attn/q_proj/kernel": _t(sd[p + "self_attn.q_proj.weight"]).reshape(h, nh, d),
            "self_attn/k_proj/kernel": _t(sd[p + "self_attn.k_proj.weight"]).reshape(h, nkv, d),
            "self_attn/v_proj/kernel": _t(sd[p + "self_attn.v_proj.weight"]).reshape(h, nkv, d),
            "self_attn/o_proj/kernel": _t(sd[p + "self_attn.o_proj.weight"]).reshape(nh, d, h),
            "input_layernorm/weight": _np(sd[p + "input_layernorm.weight"]),
            "post_attention_layernorm/weight": _np(sd[p + "post_attention_layernorm.weight"]),
            "moe/router": _t(sd[m + "gate.weight"]),
            # HF experts: w1=gate (f,h), w3=up (f,h), w2=down (h,f); ours are
            # stacked (E, in, out).
            "moe/w_gate": np.stack([_t(sd[m + f"experts.{e}.w1.weight"]) for e in range(E)]),
            "moe/w_up": np.stack([_t(sd[m + f"experts.{e}.w3.weight"]) for e in range(E)]),
            "moe/w_down": np.stack([_t(sd[m + f"experts.{e}.w2.weight"]) for e in range(E)]),
        })
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "model/layers/block", "model/layers_{i}", cfg.num_hidden_layers)
    return tree


# ---------------------------------------------------------------------------
# GPT-2
# ---------------------------------------------------------------------------

def gpt2_config_from_hf(hf: Any) -> "GPT2Config":
    from .gpt2 import GPT2Config

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return GPT2Config(
        vocab_size=g("vocab_size"),
        n_positions=g("n_positions", 1024),
        n_embd=g("n_embd", 768),
        n_layer=g("n_layer", 12),
        n_head=g("n_head", 12),
        layer_norm_epsilon=g("layer_norm_epsilon", 1e-5),
    )


def gpt2_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, d = cfg.n_embd, cfg.n_head, cfg.head_dim
    # transformers GPT2Model state dicts may or may not carry the
    # "transformer." prefix depending on the head class.
    pref = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    tree: dict = {"transformer": {}}
    _set(tree, "transformer/wte/embedding", _np(sd[pref + "wte.weight"]))
    _set(tree, "transformer/wpe/embedding", _np(sd[pref + "wpe.weight"]))
    _set(tree, "transformer/ln_f/scale", _np(sd[pref + "ln_f.weight"]))
    _set(tree, "transformer/ln_f/bias", _np(sd[pref + "ln_f.bias"]))
    layers = []
    for i in range(cfg.n_layer):
        p = f"{pref}h.{i}."
        # GPT-2 Conv1D stores weights (in, out) — already the flax kernel
        # layout, no transpose.
        layers.append({
            "ln_1/scale": _np(sd[p + "ln_1.weight"]),
            "ln_1/bias": _np(sd[p + "ln_1.bias"]),
            "attn/c_attn/kernel": _np(sd[p + "attn.c_attn.weight"]).reshape(h, 3, nh, d),
            "attn/c_attn/bias": _np(sd[p + "attn.c_attn.bias"]).reshape(3, nh, d),
            "attn/c_proj/kernel": _np(sd[p + "attn.c_proj.weight"]).reshape(nh, d, h),
            "attn/c_proj/bias": _np(sd[p + "attn.c_proj.bias"]),
            "ln_2/scale": _np(sd[p + "ln_2.weight"]),
            "ln_2/bias": _np(sd[p + "ln_2.bias"]),
            "c_fc/kernel": _np(sd[p + "mlp.c_fc.weight"]),
            "c_fc/bias": _np(sd[p + "mlp.c_fc.bias"]),
            "c_proj/kernel": _np(sd[p + "mlp.c_proj.weight"]),
            "c_proj/bias": _np(sd[p + "mlp.c_proj.bias"]),
        })
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "transformer/h/block", "transformer/h_{i}", cfg.n_layer)
    return tree


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------

def bert_config_from_hf(hf: Any, num_labels: int = 2) -> "BertConfig":
    from .bert import BertConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return BertConfig(
        vocab_size=g("vocab_size"),
        hidden_size=g("hidden_size"),
        num_hidden_layers=g("num_hidden_layers"),
        num_attention_heads=g("num_attention_heads"),
        intermediate_size=g("intermediate_size"),
        max_position_embeddings=g("max_position_embeddings", 512),
        type_vocab_size=g("type_vocab_size", 2),
        layer_norm_eps=g("layer_norm_eps", 1e-12),
        hidden_dropout_prob=g("hidden_dropout_prob", 0.1),
        num_labels=g("num_labels", num_labels),
    )


def bert_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, d = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    pref = "bert." if any(k.startswith("bert.") for k in sd) else ""
    e = pref + "embeddings."
    tree: dict = {"bert": {}}
    _set(tree, "bert/word_embeddings/embedding", _np(sd[e + "word_embeddings.weight"]))
    _set(tree, "bert/position_embeddings/embedding", _np(sd[e + "position_embeddings.weight"]))
    _set(tree, "bert/token_type_embeddings/embedding", _np(sd[e + "token_type_embeddings.weight"]))
    _set(tree, "bert/embeddings_norm/scale", _np(sd[e + "LayerNorm.weight"]))
    _set(tree, "bert/embeddings_norm/bias", _np(sd[e + "LayerNorm.bias"]))
    if pref + "pooler.dense.weight" in sd:
        _set(tree, "bert/pooler/kernel", _t(sd[pref + "pooler.dense.weight"]))
        _set(tree, "bert/pooler/bias", _np(sd[pref + "pooler.dense.bias"]))
    if "classifier.weight" in sd:
        _set(tree, "classifier/kernel", _t(sd["classifier.weight"]))
        _set(tree, "classifier/bias", _np(sd["classifier.bias"]))
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"{pref}encoder.layer.{i}."
        layers.append({
            "attention/query/kernel": _t(sd[p + "attention.self.query.weight"]).reshape(h, nh, d),
            "attention/query/bias": _np(sd[p + "attention.self.query.bias"]).reshape(nh, d),
            "attention/key/kernel": _t(sd[p + "attention.self.key.weight"]).reshape(h, nh, d),
            "attention/key/bias": _np(sd[p + "attention.self.key.bias"]).reshape(nh, d),
            "attention/value/kernel": _t(sd[p + "attention.self.value.weight"]).reshape(h, nh, d),
            "attention/value/bias": _np(sd[p + "attention.self.value.bias"]).reshape(nh, d),
            "attention/output/kernel": _t(sd[p + "attention.output.dense.weight"]).reshape(nh, d, h),
            "attention/output/bias": _np(sd[p + "attention.output.dense.bias"]),
            "attention_norm/scale": _np(sd[p + "attention.output.LayerNorm.weight"]),
            "attention_norm/bias": _np(sd[p + "attention.output.LayerNorm.bias"]),
            "intermediate/kernel": _t(sd[p + "intermediate.dense.weight"]),
            "intermediate/bias": _np(sd[p + "intermediate.dense.bias"]),
            "output/kernel": _t(sd[p + "output.dense.weight"]),
            "output/bias": _np(sd[p + "output.dense.bias"]),
            "output_norm/scale": _np(sd[p + "output.LayerNorm.weight"]),
            "output_norm/bias": _np(sd[p + "output.LayerNorm.bias"]),
        })
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "bert/layers/block", "bert/layer_{i}", cfg.num_hidden_layers)
    return tree


# ---------------------------------------------------------------------------
# Whisper
# ---------------------------------------------------------------------------

def whisper_config_from_hf(hf: Any) -> "WhisperConfig":
    from .whisper import WhisperConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return WhisperConfig(
        vocab_size=g("vocab_size"),
        num_mel_bins=g("num_mel_bins", 80),
        d_model=g("d_model"),
        encoder_layers=g("encoder_layers"),
        decoder_layers=g("decoder_layers"),
        encoder_attention_heads=g("encoder_attention_heads"),
        decoder_attention_heads=g("decoder_attention_heads"),
        encoder_ffn_dim=g("encoder_ffn_dim"),
        decoder_ffn_dim=g("decoder_ffn_dim"),
        max_source_positions=g("max_source_positions", 1500),
        max_target_positions=g("max_target_positions", 448),
    )


def _whisper_attn(sd, p, dm, nh, d) -> dict:
    out = {
        "q_proj/kernel": _t(sd[p + "q_proj.weight"]).reshape(dm, nh, d),
        "q_proj/bias": _np(sd[p + "q_proj.bias"]).reshape(nh, d),
        "k_proj/kernel": _t(sd[p + "k_proj.weight"]).reshape(dm, nh, d),  # no bias
        "v_proj/kernel": _t(sd[p + "v_proj.weight"]).reshape(dm, nh, d),
        "v_proj/bias": _np(sd[p + "v_proj.bias"]).reshape(nh, d),
        "out_proj/kernel": _t(sd[p + "out_proj.weight"]).reshape(nh, d, dm),
        "out_proj/bias": _np(sd[p + "out_proj.bias"]),
    }
    return out


def whisper_params_from_hf(cfg, sd: dict) -> dict:
    dm = cfg.d_model
    pref = "model." if any(k.startswith("model.") for k in sd) else ""
    tree: dict = {"encoder": {}, "decoder": {}}
    e = pref + "encoder."
    # torch Conv1d (out, in, k) → flax (k, in, out).
    _set(tree, "encoder/conv1/kernel", _np(sd[e + "conv1.weight"]).transpose(2, 1, 0))
    _set(tree, "encoder/conv1/bias", _np(sd[e + "conv1.bias"]))
    _set(tree, "encoder/conv2/kernel", _np(sd[e + "conv2.weight"]).transpose(2, 1, 0))
    _set(tree, "encoder/conv2/bias", _np(sd[e + "conv2.bias"]))
    _set(tree, "encoder/embed_positions", _np(sd[e + "embed_positions.weight"]))
    _set(tree, "encoder/layer_norm/scale", _np(sd[e + "layer_norm.weight"]))
    _set(tree, "encoder/layer_norm/bias", _np(sd[e + "layer_norm.bias"]))
    d_ = pref + "decoder."
    _set(tree, "decoder/embed_tokens/embedding", _np(sd[d_ + "embed_tokens.weight"]))
    _set(tree, "decoder/embed_positions/embedding", _np(sd[d_ + "embed_positions.weight"]))
    _set(tree, "decoder/layer_norm/scale", _np(sd[d_ + "layer_norm.weight"]))
    _set(tree, "decoder/layer_norm/bias", _np(sd[d_ + "layer_norm.bias"]))

    def _block(p, cross: bool) -> dict:
        # Encoder and decoder stacks may differ in head count; reshape each
        # with ITS heads (review finding: encoder dims were used for both).
        nh, d = (
            (cfg.decoder_attention_heads, cfg.decoder_head_dim)
            if cross else (cfg.encoder_attention_heads, cfg.head_dim)
        )
        layer = {}
        for k, v in _whisper_attn(sd, p + "self_attn.", dm, nh, d).items():
            layer[f"self_attn/{k}"] = v
        layer["self_attn_layer_norm/scale"] = _np(sd[p + "self_attn_layer_norm.weight"])
        layer["self_attn_layer_norm/bias"] = _np(sd[p + "self_attn_layer_norm.bias"])
        if cross:
            for k, v in _whisper_attn(sd, p + "encoder_attn.", dm, nh, d).items():
                layer[f"encoder_attn/{k}"] = v
            layer["encoder_attn_layer_norm/scale"] = _np(sd[p + "encoder_attn_layer_norm.weight"])
            layer["encoder_attn_layer_norm/bias"] = _np(sd[p + "encoder_attn_layer_norm.bias"])
        layer["fc1/kernel"] = _t(sd[p + "fc1.weight"])
        layer["fc1/bias"] = _np(sd[p + "fc1.bias"])
        layer["fc2/kernel"] = _t(sd[p + "fc2.weight"])
        layer["fc2/bias"] = _np(sd[p + "fc2.bias"])
        layer["final_layer_norm/scale"] = _np(sd[p + "final_layer_norm.weight"])
        layer["final_layer_norm/bias"] = _np(sd[p + "final_layer_norm.bias"])
        return layer

    enc_layers = [_block(f"{e}layers.{i}.", False) for i in range(cfg.encoder_layers)]
    dec_layers = [_block(f"{d_}layers.{i}.", True) for i in range(cfg.decoder_layers)]
    _place_layers(tree["encoder"], _stack_layers(enc_layers), cfg.scan_layers,
                  "layers/block", "layer_{i}", cfg.encoder_layers)
    _place_layers(tree["decoder"], _stack_layers(dec_layers), cfg.scan_layers,
                  "layers/block", "layer_{i}", cfg.decoder_layers)
    return tree


# ---------------------------------------------------------------------------
# GPT-NeoX
# ---------------------------------------------------------------------------

def neox_config_from_hf(hf: Any) -> "GPTNeoXConfig":
    from .neox import GPTNeoXConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return GPTNeoXConfig(
        vocab_size=g("vocab_size"),
        hidden_size=g("hidden_size"),
        num_hidden_layers=g("num_hidden_layers"),
        num_attention_heads=g("num_attention_heads"),
        intermediate_size=g("intermediate_size"),
        rotary_pct=g("rotary_pct", 0.25),
        rotary_emb_base=g("rotary_emb_base", 10000.0),
        layer_norm_eps=g("layer_norm_eps", 1e-5),
        use_parallel_residual=bool(g("use_parallel_residual", True)),
        max_position_embeddings=g("max_position_embeddings", 2048),
    )


def neox_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, d = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    pref = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
    tree: dict = {"gpt_neox": {}}
    _set(tree, "gpt_neox/embed_in/embedding", _np(sd[pref + "embed_in.weight"]))
    _set(tree, "gpt_neox/final_layer_norm/scale", _np(sd[pref + "final_layer_norm.weight"]))
    _set(tree, "gpt_neox/final_layer_norm/bias", _np(sd[pref + "final_layer_norm.bias"]))
    _set(tree, "embed_out/kernel", _t(sd["embed_out.weight"]))
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"{pref}layers.{i}."
        layers.append({
            "input_layernorm/scale": _np(sd[p + "input_layernorm.weight"]),
            "input_layernorm/bias": _np(sd[p + "input_layernorm.bias"]),
            # (3H, H) with per-head [q|k|v] rows → (H, nh, 3, d).
            "attention/query_key_value/kernel": _t(sd[p + "attention.query_key_value.weight"]).reshape(h, nh, 3, d),
            "attention/query_key_value/bias": _np(sd[p + "attention.query_key_value.bias"]).reshape(nh, 3, d),
            "attention/dense/kernel": _t(sd[p + "attention.dense.weight"]).reshape(nh, d, h),
            "attention/dense/bias": _np(sd[p + "attention.dense.bias"]),
            "post_attention_layernorm/scale": _np(sd[p + "post_attention_layernorm.weight"]),
            "post_attention_layernorm/bias": _np(sd[p + "post_attention_layernorm.bias"]),
            "dense_h_to_4h/kernel": _t(sd[p + "mlp.dense_h_to_4h.weight"]),
            "dense_h_to_4h/bias": _np(sd[p + "mlp.dense_h_to_4h.bias"]),
            "dense_4h_to_h/kernel": _t(sd[p + "mlp.dense_4h_to_h.weight"]),
            "dense_4h_to_h/bias": _np(sd[p + "mlp.dense_4h_to_h.bias"]),
        })
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "gpt_neox/layers/block", "gpt_neox/layer_{i}", cfg.num_hidden_layers)
    return tree


# ---------------------------------------------------------------------------
# OPT
# ---------------------------------------------------------------------------

def opt_config_from_hf(hf: Any) -> "OPTConfig":
    from .opt import OPTConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return OPTConfig(
        vocab_size=g("vocab_size"),
        hidden_size=g("hidden_size"),
        ffn_dim=g("ffn_dim"),
        num_hidden_layers=g("num_hidden_layers"),
        num_attention_heads=g("num_attention_heads"),
        max_position_embeddings=g("max_position_embeddings", 2048),
    )


def opt_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, d = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    pref = "model.decoder." if any(k.startswith("model.decoder.") for k in sd) else "decoder."
    tree: dict = {"model": {}}
    _set(tree, "model/embed_tokens/embedding", _np(sd[pref + "embed_tokens.weight"]))
    _set(tree, "model/embed_positions/embedding", _np(sd[pref + "embed_positions.weight"]))
    _set(tree, "model/final_layer_norm/scale", _np(sd[pref + "final_layer_norm.weight"]))
    _set(tree, "model/final_layer_norm/bias", _np(sd[pref + "final_layer_norm.bias"]))
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"{pref}layers.{i}."
        layer = {}
        for name in ("q_proj", "k_proj", "v_proj"):
            layer[f"self_attn/{name}/kernel"] = _t(sd[p + f"self_attn.{name}.weight"]).reshape(h, nh, d)
            layer[f"self_attn/{name}/bias"] = _np(sd[p + f"self_attn.{name}.bias"]).reshape(nh, d)
        layer["self_attn/out_proj/kernel"] = _t(sd[p + "self_attn.out_proj.weight"]).reshape(nh, d, h)
        layer["self_attn/out_proj/bias"] = _np(sd[p + "self_attn.out_proj.bias"])
        layer["self_attn_layer_norm/scale"] = _np(sd[p + "self_attn_layer_norm.weight"])
        layer["self_attn_layer_norm/bias"] = _np(sd[p + "self_attn_layer_norm.bias"])
        layer["fc1/kernel"] = _t(sd[p + "fc1.weight"])
        layer["fc1/bias"] = _np(sd[p + "fc1.bias"])
        layer["fc2/kernel"] = _t(sd[p + "fc2.weight"])
        layer["fc2/bias"] = _np(sd[p + "fc2.bias"])
        layer["final_layer_norm/scale"] = _np(sd[p + "final_layer_norm.weight"])
        layer["final_layer_norm/bias"] = _np(sd[p + "final_layer_norm.bias"])
        layers.append(layer)
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "model/layers/block", "model/layer_{i}", cfg.num_hidden_layers)
    return tree


# ---------------------------------------------------------------------------
# ViT
# ---------------------------------------------------------------------------

def vit_config_from_hf(hf: Any) -> "ViTConfig":
    from .vit import ViTConfig

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return ViTConfig(
        image_size=g("image_size", 224),
        patch_size=g("patch_size", 16),
        num_channels=g("num_channels", 3),
        hidden_size=g("hidden_size"),
        num_hidden_layers=g("num_hidden_layers"),
        num_attention_heads=g("num_attention_heads"),
        intermediate_size=g("intermediate_size"),
        layer_norm_eps=g("layer_norm_eps", 1e-12),
        num_labels=g("num_labels", 1000),
    )


def vit_params_from_hf(cfg, sd: dict) -> dict:
    h, nh, d = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    pref = "vit." if any(k.startswith("vit.") for k in sd) else ""
    e = pref + "embeddings."
    tree: dict = {"vit": {}}
    _set(tree, "vit/cls_token", _np(sd[e + "cls_token"]))
    _set(tree, "vit/position_embeddings", _np(sd[e + "position_embeddings"]))
    # torch Conv2d kernel (H, C, P, P) → flax NHWC Conv kernel (P, P, C, H).
    conv = _np(sd[e + "patch_embeddings.projection.weight"]).transpose(2, 3, 1, 0)
    _set(tree, "vit/patch_embed/kernel", conv)
    _set(tree, "vit/patch_embed/bias", _np(sd[e + "patch_embeddings.projection.bias"]))
    _set(tree, "vit/ln_final/scale", _np(sd[pref + "layernorm.weight"]))
    _set(tree, "vit/ln_final/bias", _np(sd[pref + "layernorm.bias"]))
    if "classifier.weight" in sd:
        _set(tree, "classifier/kernel", _t(sd["classifier.weight"]))
        _set(tree, "classifier/bias", _np(sd["classifier.bias"]))
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"{pref}encoder.layer.{i}."
        layers.append({
            "ln_before/scale": _np(sd[p + "layernorm_before.weight"]),
            "ln_before/bias": _np(sd[p + "layernorm_before.bias"]),
            "attention/query/kernel": _t(sd[p + "attention.attention.query.weight"]).reshape(h, nh, d),
            "attention/query/bias": _np(sd[p + "attention.attention.query.bias"]).reshape(nh, d),
            "attention/key/kernel": _t(sd[p + "attention.attention.key.weight"]).reshape(h, nh, d),
            "attention/key/bias": _np(sd[p + "attention.attention.key.bias"]).reshape(nh, d),
            "attention/value/kernel": _t(sd[p + "attention.attention.value.weight"]).reshape(h, nh, d),
            "attention/value/bias": _np(sd[p + "attention.attention.value.bias"]).reshape(nh, d),
            "attention/output/kernel": _t(sd[p + "attention.output.dense.weight"]).reshape(nh, d, h),
            "attention/output/bias": _np(sd[p + "attention.output.dense.bias"]),
            "ln_after/scale": _np(sd[p + "layernorm_after.weight"]),
            "ln_after/bias": _np(sd[p + "layernorm_after.bias"]),
            "intermediate/kernel": _t(sd[p + "intermediate.dense.weight"]),
            "intermediate/bias": _np(sd[p + "intermediate.dense.bias"]),
            "output/kernel": _t(sd[p + "output.dense.weight"]),
            "output/bias": _np(sd[p + "output.dense.bias"]),
        })
    _place_layers(tree, _stack_layers(layers), cfg.scan_layers,
                  "vit/layers/block", "vit/layer_{i}", cfg.num_hidden_layers)
    return tree


# ---------------------------------------------------------------------------
# T5
# ---------------------------------------------------------------------------

def t5_config_from_hf(hf: Any) -> "T5Config":
    from .t5 import T5Config

    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    return T5Config(
        vocab_size=g("vocab_size"),
        d_model=g("d_model"),
        d_kv=g("d_kv", 64),
        d_ff=g("d_ff"),
        num_layers=g("num_layers"),
        num_decoder_layers=g("num_decoder_layers"),
        num_heads=g("num_heads"),
        relative_attention_num_buckets=g("relative_attention_num_buckets", 32),
        relative_attention_max_distance=g("relative_attention_max_distance", 128),
        layer_norm_epsilon=g("layer_norm_epsilon", 1e-6),
        decoder_start_token_id=g("decoder_start_token_id", 0),
        pad_token_id=g("pad_token_id", 0),
    )


def _t5_attn(sd, p, our, dm, nh, dk) -> dict:
    return {
        f"{our}/q/kernel": _t(sd[p + "q.weight"]).reshape(dm, nh, dk),
        f"{our}/k/kernel": _t(sd[p + "k.weight"]).reshape(dm, nh, dk),
        f"{our}/v/kernel": _t(sd[p + "v.weight"]).reshape(dm, nh, dk),
        f"{our}/o/kernel": _t(sd[p + "o.weight"]).reshape(nh, dk, dm),
    }


def t5_params_from_hf(cfg, sd: dict) -> dict:
    dm, nh, dk = cfg.d_model, cfg.num_heads, cfg.d_kv
    tree: dict = {}
    _set(tree, "shared/embedding", _np(sd["shared.weight"]))
    _set(tree, "encoder/final_ln/weight", _np(sd["encoder.final_layer_norm.weight"]))
    _set(tree, "decoder/final_ln/weight", _np(sd["decoder.final_layer_norm.weight"]))

    def enc_layer(i):
        p = f"encoder.block.{i}."
        layer = _t5_attn(sd, p + "layer.0.SelfAttention.", "self_attn", dm, nh, dk)
        layer["ln0/weight"] = _np(sd[p + "layer.0.layer_norm.weight"])
        layer["ffn/wi/kernel"] = _t(sd[p + "layer.1.DenseReluDense.wi.weight"])
        layer["ffn/wo/kernel"] = _t(sd[p + "layer.1.DenseReluDense.wo.weight"])
        layer["ln1/weight"] = _np(sd[p + "layer.1.layer_norm.weight"])
        return layer

    def dec_layer(i):
        p = f"decoder.block.{i}."
        layer = _t5_attn(sd, p + "layer.0.SelfAttention.", "self_attn", dm, nh, dk)
        layer["ln0/weight"] = _np(sd[p + "layer.0.layer_norm.weight"])
        layer.update(_t5_attn(sd, p + "layer.1.EncDecAttention.", "cross_attn", dm, nh, dk))
        layer["ln1/weight"] = _np(sd[p + "layer.1.layer_norm.weight"])
        layer["ffn/wi/kernel"] = _t(sd[p + "layer.2.DenseReluDense.wi.weight"])
        layer["ffn/wo/kernel"] = _t(sd[p + "layer.2.DenseReluDense.wo.weight"])
        layer["ln2/weight"] = _np(sd[p + "layer.2.layer_norm.weight"])
        return layer

    for stack, n, make in (("encoder", cfg.num_layers, enc_layer),
                           ("decoder", cfg.n_dec, dec_layer)):
        first = make(0)
        first["self_attn/relative_attention_bias/embedding"] = _np(
            sd[f"{stack}.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
        )
        for path, arr in first.items():
            _set(tree, f"{stack}/block_0/{path}", arr)
        rest = [make(i) for i in range(1, n)]
        if rest and cfg.scan_layers:
            for path, arr in _stack_layers(rest).items():
                _set(tree, f"{stack}/layers/block/{path}", arr)
        else:
            # unscanned names are block_1..block_{n-1}
            for i in range(1, n):
                for path, arr in rest[i - 1].items():
                    _set(tree, f"{stack}/block_{i}/{path}", arr)
    return tree


# ---------------------------------------------------------------------------
# Phi-3 (Llama architecture with fused qkv_proj / gate_up_proj)
# ---------------------------------------------------------------------------

def phi3_config_from_hf(hf: Any) -> "LlamaConfig":
    """Llama config + guards for the Phi-3 variants the plain-RoPE Llama
    family cannot represent: longrope scaling (Phi-3-mini-128k) and partial
    rotary (Phi-4-mini) would convert silently and diverge at every token."""
    g = (lambda k, d=None: hf.get(k, d)) if isinstance(hf, dict) else (
        lambda k, d=None: getattr(hf, k, d)
    )
    scaling = g("rope_scaling")
    if scaling:
        raise ValueError(
            f"Phi-3 checkpoint uses rope_scaling={scaling.get('type', scaling) if isinstance(scaling, dict) else scaling!r} "
            "— longrope is not supported by the Llama family; load the base "
            "(4k) variant instead."
        )
    partial = g("partial_rotary_factor", 1.0)
    if partial not in (None, 1.0):
        raise ValueError(
            f"Phi-3 checkpoint uses partial_rotary_factor={partial} — the "
            "Llama family applies full-head RoPE only."
        )
    return llama_config_from_hf(hf)


def phi3_params_from_hf(cfg, sd: dict) -> dict:
    """Split Phi-3's fused projections into the Llama family's layout:
    qkv_proj rows are [q (Hq·d) | k (Hkv·d) | v (Hkv·d)], gate_up_proj rows
    are [gate (I) | up (I)]; everything else is byte-identical Llama."""
    q_rows = cfg.num_attention_heads * cfg.head_dim
    kv_rows = cfg.num_key_value_heads * cfg.head_dim
    split: dict = {}
    for k, v in sd.items():
        if k.endswith("self_attn.qkv_proj.weight"):
            base = k[: -len("qkv_proj.weight")]
            w = _np(v)
            split[base + "q_proj.weight"] = w[:q_rows]
            split[base + "k_proj.weight"] = w[q_rows:q_rows + kv_rows]
            split[base + "v_proj.weight"] = w[q_rows + kv_rows:]
        elif k.endswith("mlp.gate_up_proj.weight"):
            base = k[: -len("gate_up_proj.weight")]
            w = _np(v)
            split[base + "gate_proj.weight"] = w[: cfg.intermediate_size]
            split[base + "up_proj.weight"] = w[cfg.intermediate_size:]
        else:
            split[k] = v
    return llama_params_from_hf(cfg, split)


# ---------------------------------------------------------------------------
# CLIP
# ---------------------------------------------------------------------------

def clip_config_from_hf(hf: Any) -> "CLIPConfig":
    from .clip import CLIPConfig

    if isinstance(hf, dict):
        text, vision = hf.get("text_config", {}), hf.get("vision_config", {})
        tg = lambda k, d=None: text.get(k, d)  # noqa: E731
        vg = lambda k, d=None: vision.get(k, d)  # noqa: E731
        g = lambda k, d=None: hf.get(k, d)  # noqa: E731
    else:
        tg = lambda k, d=None: getattr(hf.text_config, k, d)  # noqa: E731
        vg = lambda k, d=None: getattr(hf.vision_config, k, d)  # noqa: E731
        g = lambda k, d=None: getattr(hf, k, d)  # noqa: E731
    return CLIPConfig(
        vocab_size=tg("vocab_size"),
        text_hidden_size=tg("hidden_size"),
        text_num_layers=tg("num_hidden_layers"),
        text_num_heads=tg("num_attention_heads"),
        text_intermediate_size=tg("intermediate_size"),
        max_position_embeddings=tg("max_position_embeddings", 77),
        image_size=vg("image_size", 224),
        patch_size=vg("patch_size", 32),
        num_channels=vg("num_channels", 3),
        vision_hidden_size=vg("hidden_size"),
        vision_num_layers=vg("num_hidden_layers"),
        vision_num_heads=vg("num_attention_heads"),
        vision_intermediate_size=vg("intermediate_size"),
        projection_dim=g("projection_dim", 512),
        logit_scale_init=g("logit_scale_init_value", 2.6592),
        layer_norm_eps=_clip_ln_eps(tg, vg),
        eos_token_id=tg("eos_token_id", 49407),
        hidden_act=_clip_hidden_act(tg, vg),
    )


def _clip_ln_eps(tg, vg) -> float:
    text_eps = tg("layer_norm_eps", 1e-5)
    vision_eps = vg("layer_norm_eps", 1e-5)
    if text_eps != vision_eps:
        raise ValueError(
            f"CLIP checkpoint mixes tower layer_norm_eps (text={text_eps}, "
            f"vision={vision_eps}) — not supported by the native family."
        )
    return text_eps


def _clip_hidden_act(tg, vg) -> str:
    text_act = tg("hidden_act", "quick_gelu")
    vision_act = vg("hidden_act", "quick_gelu")
    if text_act != vision_act:
        raise ValueError(
            f"CLIP checkpoint mixes tower activations (text={text_act!r}, "
            f"vision={vision_act!r}) — not supported by the native family."
        )
    return text_act


def _clip_tower_layers(sd, prefix, n, h, nh):
    d = h // nh
    layers = []
    for i in range(n):
        p = f"{prefix}.encoder.layers.{i}."
        layers.append({
            "ln1/scale": _np(sd[p + "layer_norm1.weight"]),
            "ln1/bias": _np(sd[p + "layer_norm1.bias"]),
            "self_attn/q_proj/kernel": _t(sd[p + "self_attn.q_proj.weight"]).reshape(h, nh, d),
            "self_attn/q_proj/bias": _np(sd[p + "self_attn.q_proj.bias"]).reshape(nh, d),
            "self_attn/k_proj/kernel": _t(sd[p + "self_attn.k_proj.weight"]).reshape(h, nh, d),
            "self_attn/k_proj/bias": _np(sd[p + "self_attn.k_proj.bias"]).reshape(nh, d),
            "self_attn/v_proj/kernel": _t(sd[p + "self_attn.v_proj.weight"]).reshape(h, nh, d),
            "self_attn/v_proj/bias": _np(sd[p + "self_attn.v_proj.bias"]).reshape(nh, d),
            "self_attn/out_proj/kernel": _t(sd[p + "self_attn.out_proj.weight"]).reshape(nh, d, h),
            "self_attn/out_proj/bias": _np(sd[p + "self_attn.out_proj.bias"]),
            "ln2/scale": _np(sd[p + "layer_norm2.weight"]),
            "ln2/bias": _np(sd[p + "layer_norm2.bias"]),
            "fc1/kernel": _t(sd[p + "mlp.fc1.weight"]),
            "fc1/bias": _np(sd[p + "mlp.fc1.bias"]),
            "fc2/kernel": _t(sd[p + "mlp.fc2.weight"]),
            "fc2/bias": _np(sd[p + "mlp.fc2.bias"]),
        })
    return layers


def clip_params_from_hf(cfg, sd: dict) -> dict:
    tree: dict = {"text": {}, "vision": {}}
    # Text tower
    _set(tree, "text/token_embedding", _np(sd["text_model.embeddings.token_embedding.weight"]))
    _set(tree, "text/position_embedding", _np(sd["text_model.embeddings.position_embedding.weight"]))
    _set(tree, "text/final_ln/scale", _np(sd["text_model.final_layer_norm.weight"]))
    _set(tree, "text/final_ln/bias", _np(sd["text_model.final_layer_norm.bias"]))
    _place_layers(
        tree,
        _stack_layers(_clip_tower_layers(
            sd, "text_model", cfg.text_num_layers, cfg.text_hidden_size, cfg.text_num_heads
        )),
        cfg.scan_layers, "text/layers/block", "text/layer_{i}", cfg.text_num_layers,
    )
    # Vision tower (note: HF spells it "pre_layrnorm")
    _set(tree, "vision/class_embedding", _np(sd["vision_model.embeddings.class_embedding"]))
    conv = _np(sd["vision_model.embeddings.patch_embedding.weight"]).transpose(2, 3, 1, 0)
    _set(tree, "vision/patch_embed/kernel", conv)
    _set(tree, "vision/position_embedding", _np(sd["vision_model.embeddings.position_embedding.weight"]))
    _set(tree, "vision/pre_ln/scale", _np(sd["vision_model.pre_layrnorm.weight"]))
    _set(tree, "vision/pre_ln/bias", _np(sd["vision_model.pre_layrnorm.bias"]))
    _set(tree, "vision/post_ln/scale", _np(sd["vision_model.post_layernorm.weight"]))
    _set(tree, "vision/post_ln/bias", _np(sd["vision_model.post_layernorm.bias"]))
    _place_layers(
        tree,
        _stack_layers(_clip_tower_layers(
            sd, "vision_model", cfg.vision_num_layers, cfg.vision_hidden_size,
            cfg.vision_num_heads,
        )),
        cfg.scan_layers, "vision/layers/block", "vision/layer_{i}", cfg.vision_num_layers,
    )
    _set(tree, "text_projection/kernel", _t(sd["text_projection.weight"]))
    _set(tree, "visual_projection/kernel", _t(sd["visual_projection.weight"]))
    _set(tree, "logit_scale", _np(sd["logit_scale"]))
    return tree


# ---------------------------------------------------------------------------
# High-level entry
# ---------------------------------------------------------------------------

_FAMILIES = {
    "clip": ("CLIPModel", clip_config_from_hf, clip_params_from_hf),
    "llama": ("LlamaForCausalLM", llama_config_from_hf, llama_params_from_hf),
    "mistral": ("LlamaForCausalLM", llama_config_from_hf, llama_params_from_hf),
    "qwen2": ("LlamaForCausalLM", llama_config_from_hf, llama_params_from_hf),
    "gemma": ("LlamaForCausalLM", gemma_config_from_hf, llama_params_from_hf),
    "phi3": ("LlamaForCausalLM", phi3_config_from_hf, phi3_params_from_hf),
    "mixtral": ("MixtralForCausalLM", mixtral_config_from_hf, mixtral_params_from_hf),
    "gpt2": ("GPT2LMHeadModel", gpt2_config_from_hf, gpt2_params_from_hf),
    "bert": ("BertForSequenceClassification", bert_config_from_hf, bert_params_from_hf),
    "t5": ("T5ForConditionalGeneration", t5_config_from_hf, t5_params_from_hf),
    "vit": ("ViTForImageClassification", vit_config_from_hf, vit_params_from_hf),
    "opt": ("OPTForCausalLM", opt_config_from_hf, opt_params_from_hf),
    "gpt_neox": ("GPTNeoXForCausalLM", neox_config_from_hf, neox_params_from_hf),
    "whisper": ("WhisperForConditionalGeneration", whisper_config_from_hf, whisper_params_from_hf),
}


def _read_checkpoint_dir(path: str) -> tuple[dict, dict]:
    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    sd: dict = {}
    shards = sorted(fn for fn in os.listdir(path) if fn.endswith(".safetensors"))
    if shards:
        from safetensors.numpy import load_file

        for fn in shards:
            sd.update(load_file(os.path.join(path, fn)))
    elif os.path.exists(os.path.join(path, "pytorch_model.bin")):
        import torch

        raw = torch.load(os.path.join(path, "pytorch_model.bin"),
                         map_location="cpu", weights_only=True)
        sd = {k: _np(v) for k, v in raw.items()}
    else:
        raise FileNotFoundError(f"No *.safetensors or pytorch_model.bin under {path}")
    return hf_cfg, sd


def load_pretrained(src, family: Optional[str] = None, dtype=jnp.bfloat16):
    """HF checkpoint → (our_config, params, module_class).

    ``src``: transformers ``PreTrainedModel``, a local checkpoint directory,
    or a ``(hf_config, state_dict)`` pair.
    """
    if isinstance(src, str):
        hf_cfg, sd = _read_checkpoint_dir(src)
    elif isinstance(src, tuple):
        hf_cfg, sd = src
        sd = {k: _np(v) for k, v in sd.items()}
    else:  # transformers model instance
        hf_cfg = src.config
        sd = {k: _np(v) for k, v in src.state_dict().items()}
    if family is None:
        family = (hf_cfg.get("model_type") if isinstance(hf_cfg, dict)
                  else getattr(hf_cfg, "model_type", None))
    if family not in _FAMILIES:
        # Declarative fallback: unseen architectures load via registered
        # ArchSpec rules (models/generic_hub.py) — data, not new code.
        from . import generic_hub

        spec = generic_hub.get_arch_spec(family)
        if spec is not None:
            return generic_hub.load_with_spec(spec, hf_cfg, sd, dtype)
        known = ", ".join(sorted(_FAMILIES))
        generic = ", ".join(generic_hub.known_generic_types())
        raise ValueError(
            f"Unsupported model family {family!r}; hand-written families: "
            f"{known}; generic specs: {generic}. Register new architectures "
            f"with accelerate_tpu.models.generic_hub.register_arch_spec."
        )
    cls_name, cfg_fn, params_fn = _FAMILIES[family]
    import dataclasses as _dc

    cfg = _dc.replace(cfg_fn(hf_cfg), dtype=dtype)
    params = params_fn(cfg, sd)
    import importlib

    models_pkg = importlib.import_module(__package__)
    return cfg, params, getattr(models_pkg, cls_name)


def model_from_pretrained(src, family: Optional[str] = None, dtype=jnp.bfloat16):
    """HF checkpoint → ready-to-run :class:`accelerate_tpu.Model`."""
    from ..model import Model

    cfg, params, cls = load_pretrained(src, family=family, dtype=dtype)
    return Model(module=cls(cfg), params=params)
