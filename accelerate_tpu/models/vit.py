"""ViT-family image classifier, TPU-first.

Vision Transformer with the same design points as the text families
(models/bert.py, models/llama.py): fused per-head DenseGeneral projections
shaped for the MXU, optional ``nn.scan`` over identical blocks, optional
remat, a Megatron-style TP rule table, bf16 compute with fp32 params. The
patch embedding is a single strided conv (NHWC — the layout XLA:TPU tiles
best); classification reads the CLS token through the final LayerNorm, the
standard ViT head. HF ``ViTForImageClassification`` checkpoints load via
models/hub.py with tested logit parity.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(unsafe_hash=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-12
    num_labels: int = 1000
    dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            image_size=32, patch_size=8, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128, num_labels=4,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def vit_base(cls, **kw):
        return cls(**kw)

    @classmethod
    def vit_large(cls, **kw):
        return cls(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16,
                   intermediate_size=4096, **kw)


class ViTSelfAttention(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        d = cfg.head_dim
        dense = partial(
            nn.DenseGeneral, features=(cfg.num_attention_heads, d), dtype=cfg.dtype,
            param_dtype=jnp.float32,
        )
        q = dense(name="query")(x)
        k = dense(name="key")(x)
        v = dense(name="value")(x)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d).astype(cfg.dtype)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1), dtype=cfg.dtype,
            param_dtype=jnp.float32, name="output",
        )(out)


class ViTBlock(nn.Module):
    """Pre-LN transformer encoder block (the ViT convention)."""

    config: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_before")(x)
        x = x + ViTSelfAttention(cfg, name="attention")(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_after")(x)
        dense = partial(nn.Dense, dtype=cfg.dtype, param_dtype=jnp.float32)
        h = dense(cfg.intermediate_size, name="intermediate")(h)
        h = nn.gelu(h, approximate=False)  # exact erf GELU (ViT convention)
        return x + dense(cfg.hidden_size, name="output")(h)


class _ScannedViTBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, _):
        return ViTBlock(self.config, name="block")(x), None


class ViTModel(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, pixel_values):
        """pixel_values: (B, H, W, C) NHWC → (B, N+1, hidden)."""
        cfg = self.config
        x = nn.Conv(
            cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
            dtype=cfg.dtype, param_dtype=jnp.float32, name="patch_embed",
        )(pixel_values.astype(cfg.dtype))
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.hidden_size)  # (B, N, H)
        cls = self.param(
            "cls_token", nn.initializers.truncated_normal(0.02),
            (1, 1, cfg.hidden_size), jnp.float32,
        )
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(x.dtype), (b, 1, cfg.hidden_size)), x], 1)
        pos = self.param(
            "position_embeddings", nn.initializers.truncated_normal(0.02),
            (1, cfg.num_patches + 1, cfg.hidden_size), jnp.float32,
        )
        x = x + pos.astype(x.dtype)

        block_cls = _ScannedViTBlock
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False)
        if cfg.scan_layers:
            scanned = nn.scan(
                block_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = scanned(cfg, name="layers")(x, None)
        else:
            blk = nn.remat(ViTBlock, prevent_cse=False) if cfg.remat else ViTBlock
            for i in range(cfg.num_hidden_layers):
                x = blk(cfg, name=f"layer_{i}")(x)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_final")(x)


class ViTForImageClassification(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, pixel_values):
        cfg = self.config
        x = ViTModel(cfg, name="vit")(pixel_values)
        return nn.Dense(
            cfg.num_labels, dtype=jnp.float32, param_dtype=jnp.float32, name="classifier"
        )(x[:, 0]).astype(jnp.float32)


def vit_tp_rules(scan_layers: bool = True) -> list[tuple[str, tuple]]:
    """Megatron column/row-parallel table for ViT (same shape as BERT's)."""
    lead = (None,) if scan_layers else ()
    return [
        (r"attention/(query|key|value)/kernel", lead + (None, "tp", None)),
        (r"attention/output/kernel", lead + ("tp", None, None)),
        (r"intermediate/kernel", lead + (None, "tp")),
        (r"(?<!attention/)output/kernel", lead + ("tp", None)),
    ]
