"""GPT-2-family decoder, TPU-first.

The reference reaches GPT training only through Megatron-LM
(reference: utils/megatron_lm.py:574-700 `GPTTrainStep`); here it is a native
flax family. Distinct from models/llama.py where it matters architecturally:
learned absolute position embeddings (no RoPE), pre-LN blocks with standard
LayerNorm (not RMSNorm), GELU MLP (not SwiGLU), fused c_attn QKV projection,
and word-embedding-tied LM head — so checkpoints keep GPT-2 layout.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .llama import _pin_last_dim_replicated


@dataclasses.dataclass(unsafe_hash=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = False
    fp8: bool = False
    fp8_format: str = "HYBRID"
    fp8_backend: str = "AUTO"      # AUTO | TE | AO | QDQ (ops/fp8.py backend_to_native)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def dot_general(self):
        if not self.fp8:
            return None
        from ..ops.fp8 import fp8_dot_general

        from ..ops.fp8 import backend_to_native

        return fp8_dot_general(self.fp8_format, native=backend_to_native(self.fp8_backend))

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(vocab_size=256, n_positions=128, n_embd=128, n_layer=2, n_head=4)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def gpt2(cls, **kw):
        return cls(**kw)

    @classmethod
    def gpt2_xl(cls, **kw):
        return cls(n_embd=1600, n_layer=48, n_head=25, **kw)


class GPT2Attention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        d = cfg.head_dim
        # Fused QKV — one big MXU matmul (GPT-2's c_attn layout).
        qkv = nn.DenseGeneral(
            features=(3, cfg.n_head, d), dtype=cfg.dtype, param_dtype=jnp.float32,
            name="c_attn",
            **({"dot_general": cfg.dot_general} if cfg.fp8 else {}),
        )(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d).astype(cfg.dtype)
        seq = x.shape[1]
        causal = jnp.tril(jnp.ones((seq, seq), bool))
        scores = jnp.where(causal[None, None], scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(
            features=cfg.n_embd, axis=(-2, -1), dtype=cfg.dtype, param_dtype=jnp.float32,
            name="c_proj",
            **({"dot_general": cfg.dot_general} if cfg.fp8 else {}),
        )(out)


class GPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_1")(x)
        x = x + GPT2Attention(cfg, name="attn")(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_2")(x)
        dense = partial(
            nn.Dense, dtype=cfg.dtype, param_dtype=jnp.float32,
            **({"dot_general": cfg.dot_general} if cfg.fp8 else {}),
        )
        h = dense(4 * cfg.n_embd, name="c_fc")(h)
        h = nn.gelu(h)
        return x + dense(cfg.n_embd, name="c_proj")(h)


class _ScannedGPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, _):
        return GPT2Block(self.config, name="block")(x), None


class GPT2Model(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="wte")(input_ids)
        x = x + nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="wpe")(
            jnp.arange(input_ids.shape[-1])
        )
        block_cls = _ScannedGPT2Block
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False)
        if cfg.scan_layers:
            scanned = nn.scan(
                block_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast,),
                length=cfg.n_layer,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = scanned(cfg, name="h")(x, None)
        else:
            blk = nn.remat(GPT2Block, prevent_cse=False) if cfg.remat else GPT2Block
            for i in range(cfg.n_layer):
                x = blk(cfg, name=f"h_{i}")(x)
        return nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_f")(x)


class GPT2LMHeadModel(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        x = GPT2Model(cfg, name="transformer")(input_ids)
        x = _pin_last_dim_replicated(x)  # FSDP propagation guard (llama.py)
        # LM head tied to wte (GPT-2 always ties).
        embedding = self.variables["params"]["transformer"]["wte"]["embedding"]
        return (x @ embedding.T.astype(cfg.dtype)).astype(jnp.float32)


def gpt2_tp_rules(scan_layers: bool = True) -> list[tuple[str, tuple]]:
    lead = (None,) if scan_layers else ()
    return [
        # Fused QKV: kernel (in, 3, heads, d) — shard heads.
        (r"attn/c_attn/kernel", lead + (None, None, "tp", None)),
        (r"attn/c_proj/kernel", lead + ("tp", None, None)),   # row-parallel
        (r"c_fc/kernel", lead + (None, "tp")),                 # column-parallel
        (r"(?<!attn/)c_proj/kernel", lead + ("tp", None)),     # row-parallel MLP out
        (r"wte/embedding", ("tp", None)),                      # vocab-sharded
    ]
