"""T5-family encoder-decoder, TPU-first.

The reference reaches T5 only through the Megatron-LM engine
(reference: utils/megatron_lm.py:640-760 ``T5TrainStep`` + model provider);
here it is a native flax family with the same design points as
models/llama.py / models/bert.py: MXU-shaped fused head projections, optional
``nn.scan`` over identical blocks, optional remat, a Megatron-style
column/row TP rule table.

Architecture follows T5 v1.0: relative-position-bias attention (bucketed,
shared from the first layer of each stack), pre-RMSNorm blocks, ReLU FFN,
tied input/output embeddings with the 1/sqrt(d_model) logits scale.
Attention keeps the additive position bias, so it uses the materialized
softmax path rather than the Pallas kernel (the kernel has no bias operand
yet); seq lengths for T5 workloads are short enough that this is the right
trade.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .llama import _pin_last_dim_replicated


@dataclasses.dataclass(unsafe_hash=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = False
    decoder_start_token_id: int = 0
    pad_token_id: int = 0

    @property
    def n_dec(self) -> int:
        return self.num_decoder_layers or self.num_layers

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2, num_heads=4,
            relative_attention_num_buckets=8, relative_attention_max_distance=32,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def t5_small(cls, **kw):
        return cls(**kw)

    @classmethod
    def t5_base(cls, **kw):
        return cls(d_model=768, d_ff=3072, num_layers=12, num_heads=12, **kw)


class T5LayerNorm(nn.Module):
    """RMS norm without bias/mean subtraction (T5 style)."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


def relative_position_bucket(relative_position, *, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """T5's log-spaced relative position bucketing (exact semantics of the
    original implementation, restated)."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5Attention(nn.Module):
    config: T5Config
    causal: bool = False
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, x, kv=None, mask=None, bias=None):
        """x: (B, Sq, D); kv: (B, Sk, D) for cross-attention (defaults to x).
        ``mask``: (B, Sk) key validity. ``bias``: precomputed position bias
        (B? 1, H, Sq, Sk) — layers past the first reuse the first layer's.
        Returns (out, bias_used)."""
        cfg = self.config
        kv = x if kv is None else kv
        sq, sk = x.shape[1], kv.shape[1]
        dense = partial(
            nn.DenseGeneral, features=(cfg.num_heads, cfg.d_kv), use_bias=False,
            dtype=cfg.dtype, param_dtype=jnp.float32,
        )
        q = dense(name="q")(x)
        k = dense(name="k")(kv)
        v = dense(name="v")(kv)
        # T5 does NOT scale by 1/sqrt(d): the initializer absorbs it.
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)

        if bias is None:
            if self.has_relative_bias:
                rel = (
                    jnp.arange(sk, dtype=jnp.int32)[None, :]
                    - jnp.arange(sq, dtype=jnp.int32)[:, None]
                )
                buckets = relative_position_bucket(
                    rel, bidirectional=not self.causal,
                    num_buckets=cfg.relative_attention_num_buckets,
                    max_distance=cfg.relative_attention_max_distance,
                )
                table = nn.Embed(
                    cfg.relative_attention_num_buckets, cfg.num_heads,
                    param_dtype=jnp.float32, name="relative_attention_bias",
                )(buckets)  # (Sq, Sk, H)
                bias = jnp.transpose(table, (2, 0, 1))[None]  # (1, H, Sq, Sk)
            else:
                bias = jnp.zeros((1, cfg.num_heads, sq, sk), jnp.float32)
            if self.causal:
                cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
                bias = jnp.where(cmask[None, None], bias, jnp.float32(-1e9))
        scores = scores + bias
        if mask is not None:
            scores = jnp.where(mask[:, None, None, :].astype(bool), scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="o",
        )(out)
        return out, bias


class T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="wi")(x)
        h = nn.relu(h)
        # NOTE: under FSDP+dp_replicate the unstacked block_0's wi kernel
        # sharding can propagate into these activations and emit one
        # involuntary-remat warning for that single block; pinning here was
        # tried and made shardy's conflict WORSE (1 -> 2 warnings) — the
        # scanned blocks (the other L-1) are clean, so this is left to the
        # partitioner. See models/llama.py:_pin_last_dim_replicated for the
        # boundary pins that do work.
        return nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name="wo")(h)


class T5EncoderBlock(nn.Module):
    config: T5Config
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, x, mask, bias):
        cfg = self.config
        h, bias = T5Attention(cfg, causal=False, has_relative_bias=self.has_relative_bias,
                              name="self_attn")(T5LayerNorm(cfg.layer_norm_epsilon,
                                                            name="ln0")(x), mask=mask, bias=bias)
        x = x + h
        x = x + T5FFN(cfg, name="ffn")(T5LayerNorm(cfg.layer_norm_epsilon, name="ln1")(x))
        return x, bias


class T5DecoderBlock(nn.Module):
    config: T5Config
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, x, enc, self_bias, enc_mask):
        cfg = self.config
        h, self_bias = T5Attention(
            cfg, causal=True, has_relative_bias=self.has_relative_bias, name="self_attn"
        )(T5LayerNorm(cfg.layer_norm_epsilon, name="ln0")(x), bias=self_bias)
        x = x + h
        h, _ = T5Attention(cfg, causal=False, name="cross_attn")(
            T5LayerNorm(cfg.layer_norm_epsilon, name="ln1")(x), kv=enc, mask=enc_mask,
        )
        x = x + h
        x = x + T5FFN(cfg, name="ffn")(T5LayerNorm(cfg.layer_norm_epsilon, name="ln2")(x))
        return x, self_bias


class T5Stack(nn.Module):
    config: T5Config
    is_decoder: bool = False

    @nn.compact
    def __call__(self, x, mask=None, enc=None, enc_mask=None):
        cfg = self.config
        n = cfg.n_dec if self.is_decoder else cfg.num_layers
        bias = None
        # First layer owns the shared relative bias; scan keeps the remaining
        # (bias-reusing) layers rolled into one compiled block.
        if self.is_decoder:
            x, bias = T5DecoderBlock(cfg, has_relative_bias=True, name="block_0")(
                x, enc, None, enc_mask
            )
        else:
            x, bias = T5EncoderBlock(cfg, has_relative_bias=True, name="block_0")(
                x, mask, None
            )
        rest = n - 1
        if rest > 0 and cfg.scan_layers:
            if self.is_decoder:

                class _Rest(nn.Module):
                    cfg_: T5Config

                    @nn.compact
                    def __call__(self, carry, _):
                        h, _ = T5DecoderBlock(self.cfg_, name="block")(
                            carry[0], carry[1], carry[2], carry[3]
                        )
                        return (h, carry[1], carry[2], carry[3]), None

                block = nn.remat(_Rest, prevent_cse=False) if cfg.remat else _Rest
                scanned = nn.scan(
                    block, variable_axes={"params": 0}, split_rngs={"params": True},
                    length=rest, metadata_params={nn.PARTITION_NAME: "layers"},
                )(cfg, name="layers")
                (x, _, _, _), _ = scanned((x, enc, bias, enc_mask), None)
            else:

                class _Rest(nn.Module):
                    cfg_: T5Config

                    @nn.compact
                    def __call__(self, carry, _):
                        h, _ = T5EncoderBlock(self.cfg_, name="block")(
                            carry[0], carry[1], carry[2]
                        )
                        return (h, carry[1], carry[2]), None

                block = nn.remat(_Rest, prevent_cse=False) if cfg.remat else _Rest
                scanned = nn.scan(
                    block, variable_axes={"params": 0}, split_rngs={"params": True},
                    length=rest, metadata_params={nn.PARTITION_NAME: "layers"},
                )(cfg, name="layers")
                (x, _, _), _ = scanned((x, mask, bias), None)
        else:
            for i in range(rest):
                if self.is_decoder:
                    x, _ = T5DecoderBlock(cfg, name=f"block_{i+1}")(x, enc, bias, enc_mask)
                else:
                    x, _ = T5EncoderBlock(cfg, name=f"block_{i+1}")(x, mask, bias)
        return T5LayerNorm(cfg.layer_norm_epsilon, name="final_ln")(x)


class T5ForConditionalGeneration(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, input_ids, decoder_input_ids, attention_mask=None):
        cfg = self.config
        embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="shared")
        if attention_mask is None:
            attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
        enc = T5Stack(cfg, is_decoder=False, name="encoder")(
            embed(input_ids), mask=attention_mask
        )
        dec = T5Stack(cfg, is_decoder=True, name="decoder")(
            embed(decoder_input_ids), enc=enc, enc_mask=attention_mask
        )
        # Tied head with the 1/sqrt(d_model) scale of untied-rescale T5.
        dec = _pin_last_dim_replicated(dec)  # FSDP propagation guard (llama.py)
        logits = (dec * (cfg.d_model ** -0.5)) @ embed.embedding.T.astype(cfg.dtype)
        return logits


def shift_tokens_right(labels, decoder_start_token_id: int = 0, pad_token_id: int = 0):
    """Teacher-forcing inputs: [start, y0, y1, ...]. Label padding (-100, the
    ignore_index of t5_cross_entropy_loss) is replaced with pad_token_id —
    negative ids would otherwise wrap around the embedding table."""
    shifted = jnp.concatenate(
        [jnp.full_like(labels[:, :1], decoder_start_token_id), labels[:, :-1]], axis=1
    )
    return jnp.where(shifted < 0, pad_token_id, shifted)


def t5_cross_entropy_loss(logits, labels, ignore_index: int = -100):
    logits = _pin_last_dim_replicated(logits)  # FSDP propagation guard
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)


def t5_tp_rules(scan_layers: bool = True) -> list[tuple[str, tuple]]:
    """Megatron column/row-parallel table for T5 (regex on "/"-joined param
    paths → dim-aligned PartitionSpec tuples; see parallel/sharding.py).
    With scan_layers, block_0 params have no leading layer dim while the
    scanned remainder does; unscanned (block_{i}) layers never do, so their
    rules match any block name."""
    if not scan_layers:
        return [
            (r"(self_attn|cross_attn)/(q|k|v)/kernel", (None, "tp", None)),
            (r"(self_attn|cross_attn)/o/kernel", ("tp", None, None)),
            (r"ffn/wi/kernel", (None, "tp")),
            (r"ffn/wo/kernel", ("tp", None)),
            (r"shared/embedding", ("tp", None)),
        ]
    return [
        # First (unscanned) blocks.
        (r"block_0/(self_attn|cross_attn)/(q|k|v)/kernel", (None, "tp", None)),
        (r"block_0/(self_attn|cross_attn)/o/kernel", ("tp", None, None)),
        (r"block_0/ffn/wi/kernel", (None, "tp")),
        (r"block_0/ffn/wo/kernel", ("tp", None)),
        # Scanned remainder (leading layer axis).
        (r"layers/block/(self_attn|cross_attn)/(q|k|v)/kernel", (None, None, "tp", None)),
        (r"layers/block/(self_attn|cross_attn)/o/kernel", (None, "tp", None, None)),
        (r"layers/block/ffn/wi/kernel", (None, None, "tp")),
        (r"layers/block/ffn/wo/kernel", (None, "tp", None)),
        # Shared embedding table shards the vocab dim.
        (r"shared/embedding", ("tp", None)),
    ]
