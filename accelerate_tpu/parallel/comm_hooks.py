"""DDP gradient-compression communication hooks (PowerSGD, fp16/bf16).

Reference: ``DistributedDataParallelKwargs.register_comm_hook``
(src/accelerate/utils/dataclasses.py:157-241) lets DDP users swap the bucket
all-reduce for fp16/bf16-compressed or PowerSGD low-rank reduction. Under
GSPMD the DP gradient mean is a compiler-placed ``psum`` inside the jitted
step, so there is no reducer object to patch; taking control of the
communication means computing the gradients under ``shard_map`` over the DP
axes (no automatic psum) and reducing them manually. These helpers are that
manual reduction:

- ``"fp16"`` / ``"bf16"``: cast → ``pmean`` → cast back. Halves the bits on
  the wire; on DCN-spanning meshes (multi-pod data parallel) that is the
  difference between hiding the grad sync behind compute or not.
- ``"powersgd"``: rank-r power-iteration compression (Vogels et al., 2019)
  with error feedback. Per 2-D+ gradient ``M (n×m)``: ``P = M@Q`` (pmean,
  orthonormalize), ``Q' = Mᵀ@P`` (pmean), ``M̂ = P@Q'ᵀ``; the residual
  ``M - M̂`` carries into the next step's gradient. Communication per tensor
  drops from ``n·m`` to ``r·(n+m)``. This is *algorithmic* compression GSPMD
  can never insert on its own (VERDICT r3 missing #4).

Used by ``Accelerator.prepare_train_step`` when
``DistributedDataParallelKwargs(comm_hook=...)`` is passed — see
``Accelerator._comm_hook_step``. 1-D tensors (norm scales, biases)
and tensors with ``min(n, m) <= rank`` always reduce with a plain ``pmean``:
there is nothing to compress.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

COMM_HOOKS = ("no", "fp16", "bf16", "powersgd")


def _matrix_shape(g) -> tuple[int, int]:
    return g.shape[0], math.prod(g.shape[1:])


def _compressible(g, rank: int) -> bool:
    if getattr(g, "ndim", 0) < 2:
        return False
    n, m = _matrix_shape(g)
    # Below this point the factors P (n·r) + Q (m·r) cost as much wire as M.
    return min(n, m) > rank and rank * (n + m) < n * m


def init_powersgd_state(params, rank: int, dp_size: int = 1, seed: int = 0,
                        mesh=None, dp_axes: tuple = ()):
    """Per-compressible-leaf ``{"q": (m, r) start vectors, "e": (dp, n, m)
    error feedback}``; non-compressible leaves get an empty dict.

    Q starts from a fixed-seed normal and STAYS identical on every DP rank
    (each update is pmean'd). The error feedback is genuinely per-worker —
    ``e_new = local_grad + e - approx`` diverges across ranks by design
    (Vogels et al. §3) — so it carries an explicit leading ``dp`` axis and is
    declared SHARDED over the DP mesh axes, never replicated: a dishonest
    replication claim would let any relayout/checkpoint silently collapse
    all workers' residuals to rank 0's copy.

    Pass ``mesh``/``dp_axes`` to allocate the buffers directly with their
    target shardings — without it a (dp, n, m) zeros per large leaf would
    materialize dp× the param footprint on one device before the first step
    reshards it (params-scale at dp=32 means OOM at init, not at steady
    state)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    e_dev = q_dev = None
    if mesh is not None:
        e_dev = NamedSharding(mesh, P(dp_axes if dp_axes else None, None, None))
        q_dev = NamedSharding(mesh, P(None, None))
    flat, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.key(seed), max(1, len(flat)))
    states = []
    for i, p in enumerate(flat):
        if _compressible(p, rank):
            n, m = _matrix_shape(p)
            q = jax.random.normal(keys[i], (m, rank), jnp.float32)
            states.append({
                "q": jax.device_put(q, q_dev) if q_dev is not None else q,
                "e": jnp.zeros((dp_size, n, m), jnp.float32, device=e_dev),
            })
        else:
            states.append({})
    return jax.tree_util.tree_unflatten(treedef, states)


def _orthonormalize(p):
    # Thin QR: columns of p (n, r) -> orthonormal basis. r is tiny (<=32), so
    # this is MXU-trivial next to the matmuls it brackets.
    q, _ = jnp.linalg.qr(p)
    return q


def make_comm_hook_reducer(comm_hook: str, axis_names: tuple, rank: int = 8):
    """Return ``reducer(grads, comm_state) -> (reduced_grads, new_comm_state)``
    for use INSIDE ``shard_map`` over ``axis_names`` (the DP mesh axes). With
    no axes (single device) reduction degenerates to identity/compress-only.
    """
    if comm_hook not in COMM_HOOKS:
        raise ValueError(f"comm_hook must be one of {COMM_HOOKS}, got {comm_hook!r}")

    def _pmean(x):
        for ax in axis_names:
            x = jax.lax.pmean(x, ax)
        return x

    if comm_hook == "no":
        return lambda grads, comm_state: (jax.tree.map(_pmean, grads), comm_state)

    if comm_hook in ("fp16", "bf16"):
        wire = jnp.float16 if comm_hook == "fp16" else jnp.bfloat16

        def reducer(grads, comm_state):
            reduced = jax.tree.map(
                lambda g: _pmean(g.astype(wire)).astype(g.dtype), grads
            )
            return reduced, comm_state

        return reducer

    def reducer(grads, comm_state):  # powersgd
        def one(g, st):
            if not st:  # not compressible: plain mean
                return _pmean(g), st
            shape, dtype = g.shape, g.dtype
            n, m = _matrix_shape(g)
            # st["e"] arrives as this worker's slice of the (dp, n, m) error
            # buffer: leading dim 1 inside shard_map (or dp==1 standalone).
            mat = g.reshape(n, m).astype(jnp.float32) + st["e"][0]
            p = _orthonormalize(_pmean(mat @ st["q"]))
            q_new = _pmean(mat.T @ p)
            approx = p @ q_new.T
            return approx.reshape(shape).astype(dtype), {
                "q": q_new,
                "e": (mat - approx)[None],
            }

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_s = treedef.flatten_up_to(comm_state)
        out = [one(g, s) for g, s in zip(flat_g, flat_s)]
        reduced = jax.tree_util.tree_unflatten(treedef, [r for r, _ in out])
        new_state = jax.tree_util.tree_unflatten(treedef, [s for _, s in out])
        return reduced, new_state

    return reducer
