"""Ulysses/ALST sequence parallelism over the ``sp`` mesh axis.

The reference delegates to DeepSpeed's ``UlyssesSPAttentionHF`` (head-sharded
attention via all-to-all) + a sequence-sharding dataloader adapter
(reference: accelerator.py:2386-2437, docs/concept_guides/sequence_parallelism.md).
TPU-native: inputs arrive sequence-sharded over ``sp`` (the batch
PartitionSpec already shards the seq dim); inside ``shard_map`` an
``all_to_all`` reshards seq→heads, full-sequence flash attention runs on each
head group, and a second ``all_to_all`` reshards back. Collectives ride ICI.

Requires num_heads % sp == 0 (kv heads are repeated up to q heads first when
GQA would not divide)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.flash_attention import auto_flash_attention, flash_attention, _repeat_kv


def _mesh():
    from ..state import AcceleratorState

    return AcceleratorState().mesh


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mesh=None,
    axis_name: str = "sp",
):
    """q/k/v: (B, S, H, D) with S sharded over ``sp``. Returns same layout."""
    if mesh is None:
        mesh = _mesh()
    sp = mesh.shape[axis_name]
    if sp == 1:
        return auto_flash_attention(q, k, v, causal=causal, mesh=mesh)

    hq = q.shape[2]
    if hq % sp != 0:
        raise ValueError(f"num_attention_heads {hq} must divide by sp_size {sp}")
    # GQA: repeat kv heads up front so the head all-to-all is uniform.
    k, v = _repeat_kv(k, v, hq)

    spec = P(("dp_replicate", "dp_shard"), axis_name, "tp", None)

    def _local(q_c, k_c, v_c):
        # (B, S/sp, H, D) → all_to_all → (B, S, H/sp, D)
        def seq_to_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q_c), seq_to_heads(k_c), seq_to_heads(v_c)
        out = flash_attention(qh, kh, vh, causal=causal)
        return heads_to_seq(out)

    from ..utils.environment import shard_map_compat

    shard = shard_map_compat(
        _local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )
    return shard(q, k, v)
