"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

The reference reaches pipeline parallelism two ways: inference-only via
``torch.distributed.pipelining`` (reference: inference.py:75-187 —
``build_pipeline`` + ``ScheduleGPipe``) and training via the Megatron-LM
engine (reference: utils/megatron_lm.py:926, ``get_forward_backward_func``).
Both are imperative runtimes that move activations with NCCL P2P sends.

The TPU-native design is a *compiled* pipeline: one ``jax.shard_map`` manual
over the leading ``pp`` mesh axis (every other axis stays under GSPMD auto
control, so FSDP/TP/DP sharding of the non-pipeline dims composes untouched),
with the classic GPipe loop expressed as ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks and activations passed stage→stage+1
by ``lax.ppermute`` over ICI. Because ``scan``/``ppermute``/``where`` all have
transpose rules, the SAME schedule is the backward pass — ``jax.grad``
through ``pipeline_apply`` is 1F1B-shaped for free, no hand-written schedule
runtime.

Stage weights: a stack of L identical layers lives in one pytree whose leaves
have leading dim L (the ``nn.scan`` layout); sharding that dim over ``pp``
gives each stage its contiguous L/pp layers *locally* — ``shard_map`` with
``in_specs=P("pp")`` hands each stage exactly its slice, no reshapes, no
parameter movement.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# Eager-call compile cache: (stage_fn, mesh, schedule, arg structure) → jitted
# pipeline. Inside a jit trace the shard_map inlines and this is bypassed.
_EAGER_CACHE: dict = {}


def _resolve_virtual_stages(virtual_stages: Optional[int]) -> int:
    """Explicit arg > live ParallelismConfig.pp_virtual_stages > env > 1.

    The AcceleratorState peek is PASSIVE (reads the borg dict): constructing
    the singleton here would initialize the whole runtime as a side effect of
    a mesh-only pipeline_apply call — and poison a later
    Accelerator(parallelism_config=...) with 'already initialized'."""
    if virtual_stages is not None:
        v = int(virtual_stages)
        if v < 1:
            raise ValueError(f"virtual_stages must be a positive int, got {virtual_stages}")
        return v
    from ..state import AcceleratorState
    from ..utils.constants import PARALLELISM_CONFIG_PREFIX
    from ..utils.environment import get_int_from_env

    pc = AcceleratorState._shared_state.get("parallelism_config")
    if pc is not None:
        return int(getattr(pc, "pp_virtual_stages", 1) or 1)
    v = get_int_from_env([f"{PARALLELISM_CONFIG_PREFIX}PP_VIRTUAL_STAGES"], 1)
    if v < 1:
        raise ValueError(
            f"PARALLELISM_CONFIG_PP_VIRTUAL_STAGES must be a positive int, got {v}"
        )
    return v


def _active_mesh(mesh: Optional[Mesh]) -> Mesh:
    if mesh is not None:
        return mesh
    from ..state import AcceleratorState, is_initialized

    if is_initialized():
        st = AcceleratorState()
        if getattr(st, "mesh", None) is not None:
            return st.mesh
    raise ValueError("pipeline_apply needs a mesh (pass mesh= or build an Accelerator).")


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    n_microbatches: Optional[int] = None,
    axis_name: str = "pp",
    virtual_stages: Optional[int] = None,
) -> jax.Array:
    """Run ``x`` through a layer stack pipelined over the ``pp`` mesh axis.

    Args:
      stage_fn: ``(local_layer_stack, h) -> h`` — applies ONE stage's worth of
        layers to a microbatch of hidden states. Inside, leaves of
        ``local_layer_stack`` have leading dim ``L // pp`` (``L // (pp*V)``
        under interleaving). Must preserve the shape/dtype of ``h``.
      stage_params: pytree of stacked layer weights; every leaf has leading
        dim L (divisible by the ``pp`` axis size).
      x: ``(B, ...)`` hidden states; ``B`` is split into microbatches.
      n_microbatches: defaults to the ``pp`` degree (the minimum that keeps
        every stage busy outside the fill/drain bubble).
      virtual_stages: Megatron-style interleaving degree V. Each device holds
        V *non-contiguous* layer chunks (device d owns global chunks
        ``v*pp + d``) and microbatches circulate the ring V times, so the
        fill/drain bubble shrinks to ``(pp-1)/(V*m)`` of the work — the
        interleaved schedule's whole point. V>1 requires
        ``n_microbatches == pp`` per call (run several calls for larger
        batches; gradient accumulation sums them anyway). Defaults to
        ``ParallelismConfig.pp_virtual_stages`` when an Accelerator is live.

    Returns ``(B, ...)`` outputs, replicated over ``pp`` like the input.
    """
    mesh = _active_mesh(mesh)
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        return stage_fn(stage_params, x)
    v_stages = _resolve_virtual_stages(virtual_stages)
    if v_stages > 1:
        return _pipeline_apply_interleaved(
            stage_fn, stage_params, x, mesh=mesh,
            n_microbatches=n_microbatches, axis_name=axis_name,
            v_stages=v_stages,
        )

    n_micro = int(n_microbatches or n_stages)
    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch dim {batch} not divisible by n_microbatches {n_micro}")
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] % n_stages != 0:
            raise ValueError(
                f"layer-stack leading dim {leaf.shape[0]} not divisible by pp={n_stages}"
            )
    mb = batch // n_micro
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    compute_dtype = x.dtype

    def body(local_params, x_full):
        stage = jax.lax.axis_index(axis_name)
        x_full = x_full.astype(compute_dtype)
        mbs = x_full.reshape(n_micro, mb, *x_full.shape[1:])
        ticks = n_micro + n_stages - 1

        def loop(carry, t):
            state, out_buf = carry
            # Stage 0 pulls microbatch t (clamped during drain); later stages
            # consume what the previous stage sent last tick.
            mb_t = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            inp = jnp.where(stage == 0, mb_t, state)
            out = stage_fn(local_params, inp)
            # The last stage finishes microbatch (t - n_stages + 1) at tick t.
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            prev = jax.lax.dynamic_index_in_dim(out_buf, out_idx, 0, keepdims=False)
            keep = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(keep, out, prev), out_idx, 0
            )
            nxt = jax.lax.ppermute(out, axis_name, fwd)
            return (nxt, out_buf), None

        init = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        (_, out_buf), _ = jax.lax.scan(loop, init, jnp.arange(ticks))
        return out_buf

    # Each stage emits its (n_micro, mb, ...) buffer; stacking them over the
    # ``pp`` out-spec keeps the real outputs resident on the last stage with
    # NO collective at pipe exit — the slice below just addresses that block
    # and GSPMD moves it lazily wherever the consumer needs it.
    from ..utils.environment import shard_map_compat

    pipelined = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stage_params), P()),
        out_specs=P(axis_name),
        axis_names={axis_name},
        check_vma=False,
    )
    # The replicated-input spec P() makes autodiff insert a psum over ``pp``
    # for the input cotangent; a bf16 psum inside partial-manual shard_map
    # trips an XLA CPU-backend assertion, so the activation crosses the
    # boundary in f32 (cast back to the compute dtype on entry — the
    # converts fuse, and the bwd psum carries mostly zeros anyway since only
    # stage 0 reads the input).
    x_in = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    # Partial-manual shard_map only lowers under jit, and a fresh jax.jit per
    # call would retrace on every eager call — cache by schedule + argument
    # structure. Under an outer jit/grad trace the cached wrapper inlines.
    key = (
        stage_fn,
        mesh,
        axis_name,
        n_micro,
        jax.tree.structure(stage_params),
        tuple((l.shape, jnp.result_type(l)) for l in jax.tree.leaves(stage_params)),
        x_in.shape,
        jnp.result_type(x_in),
        jnp.result_type(x),  # compute dtype captured by the closure
    )
    jitted = _EAGER_CACHE.get(key)
    if jitted is None:
        jitted = _EAGER_CACHE[key] = jax.jit(pipelined)
    stacked = jitted(stage_params, x_in)
    last = stacked[(n_stages - 1) * n_micro :]
    return last.reshape(batch, *x.shape[1:])


def _pipeline_apply_interleaved(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: Optional[int],
    axis_name: str,
    v_stages: int,
) -> jax.Array:
    """Megatron-style interleaved schedule on the same synchronous ring.

    Device d owns the V non-contiguous global chunks ``{v*pp + d}``;
    microbatches circulate the ring V times. With m == pp microbatches the
    stream is conflict-free by construction: at tick t device d processes
    microbatch ``(t-d) mod pp`` at round ``(t-d) // pp`` — round-0 slots on
    device 0 are exactly the injection ticks, and no device ever has two
    ready inputs. Total ticks = V*pp + pp - 1 for V*pp units of work per
    device, so the bubble is (pp-1)/(V*pp): 1/V of GPipe's at the same m.
    Like the GPipe body, the whole schedule is one scan — ``jax.grad``
    differentiates through it, and the backward inherits the same shrunken
    bubble.
    """
    import numpy as _np

    n_stages = mesh.shape[axis_name]
    V = v_stages
    n_micro = int(n_microbatches or n_stages)
    if n_micro != n_stages:
        raise ValueError(
            f"virtual_stages>1 requires n_microbatches == pp (got m={n_micro}, "
            f"pp={n_stages}); accumulate over multiple calls for bigger batches"
        )
    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch dim {batch} not divisible by n_microbatches {n_micro}")
    n_layers = jax.tree.leaves(stage_params)[0].shape[0]
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != n_layers:
            raise ValueError(
                f"stage_params leaves disagree on layer count "
                f"({leaf.shape[0]} vs {n_layers}); jnp.take would silently "
                "clamp the shorter leaf into wrong weights"
            )
    if n_layers % (n_stages * V) != 0:
        raise ValueError(
            f"layer count {n_layers} not divisible by pp*virtual_stages="
            f"{n_stages}*{V}"
        )
    lc = n_layers // (n_stages * V)
    mb = batch // n_micro
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    compute_dtype = x.dtype

    # Re-arrange layers device-major: position (d, v, l) <- global layer
    # (v*pp + d)*lc + l, so the contiguous P("pp") shard of device d is its V
    # chunks stacked in round order. jnp.take's transpose scatters gradients
    # straight back to the caller's layout.
    perm = _np.asarray(
        [
            (v * n_stages + d) * lc + l
            for d in range(n_stages)
            for v in range(V)
            for l in range(lc)
        ],
        dtype=_np.int32,
    )

    def body(local_params, x_full):
        stage = jax.lax.axis_index(axis_name)
        x_full = x_full.astype(compute_dtype)
        mbs = x_full.reshape(n_micro, mb, *x_full.shape[1:])
        chunks = jax.tree.map(
            lambda leaf: leaf.reshape(V, lc, *leaf.shape[1:]), local_params
        )
        ticks = V * n_stages + n_stages - 1

        def loop(carry, t):
            state, out_buf = carry
            rel = t - stage
            v = jnp.clip(rel // n_stages, 0, V - 1)
            b_idx = jnp.clip(rel, 0, V * n_stages - 1) % n_stages
            chunk = jax.tree.map(
                lambda leaf: jax.lax.dynamic_index_in_dim(leaf, v, 0, keepdims=False),
                chunks,
            )
            mb_t = jax.lax.dynamic_index_in_dim(mbs, b_idx, 0, keepdims=False)
            # Device 0 injects fresh microbatches during its round-0 ticks;
            # everything else consumes the ring.
            inject = jnp.logical_and(stage == 0, rel < n_stages)
            inp = jnp.where(inject, mb_t, state)
            out = stage_fn(chunk, inp)
            # The last device completes microbatch b_idx on its final round.
            keep = jnp.logical_and(
                stage == n_stages - 1,
                jnp.logical_and(rel >= (V - 1) * n_stages, rel < V * n_stages),
            )
            prev = jax.lax.dynamic_index_in_dim(out_buf, b_idx, 0, keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(keep, out, prev), b_idx, 0
            )
            nxt = jax.lax.ppermute(out, axis_name, fwd)
            return (nxt, out_buf), None

        init = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        (_, out_buf), _ = jax.lax.scan(loop, init, jnp.arange(ticks))
        return out_buf

    from ..utils.environment import shard_map_compat

    pipelined = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stage_params), P()),
        out_specs=P(axis_name),
        axis_names={axis_name},
        check_vma=False,
    )

    def run(params, x_in):
        # Permute INSIDE the jit so XLA fuses the gather with the resharding
        # (an eager take would materialize a second copy of the whole stack
        # per call) and jnp.take's transpose scatters grads back to the
        # caller's layout.
        params_dm = jax.tree.map(lambda leaf: jnp.take(leaf, perm, axis=0), params)
        return pipelined(params_dm, x_in)

    # f32 at the replicated-input boundary: same bf16-psum workaround as the
    # GPipe path above (see the comment there).
    x_in = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    key = (
        stage_fn, mesh, axis_name, n_micro, V,
        jax.tree.structure(stage_params),
        tuple((l.shape, jnp.result_type(l)) for l in jax.tree.leaves(stage_params)),
        x_in.shape, jnp.result_type(x_in), jnp.result_type(x),
    )
    jitted = _EAGER_CACHE.get(key)
    if jitted is None:
        jitted = _EAGER_CACHE[key] = jax.jit(run)
    stacked = jitted(stage_params, x_in)
    last = stacked[(n_stages - 1) * n_micro :]
    return last.reshape(batch, *x.shape[1:])


# ---------------------------------------------------------------------------
# Flagship-model convenience: pipelined Llama forward. The embedding / final
# norm / LM head run outside the pipeline (they are not sharded over ``pp``,
# and their compute is negligible next to the block stack), matching the
# reference's first/last-stage carve-out (inference.py:101-127 feeds rank 0,
# collects on the last rank).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _llama_stage_fn(config) -> Callable:
    """Stable (per-config) stage function so eager pipeline calls hit the
    compile cache; honors ``config.remat`` per layer like the unpipelined
    ``LlamaModel`` path."""
    from ..models.llama import LlamaBlock

    block = LlamaBlock(config)

    def one_layer(carry, layer_params):
        h, positions = carry
        h = block.apply({"params": layer_params}, h, positions)
        return (h, positions), None

    if config.remat:
        one_layer = jax.checkpoint(one_layer, prevent_cse=False)

    def stage_fn(local_layers, h):
        positions = jnp.broadcast_to(
            jnp.arange(h.shape[1], dtype=jnp.int32)[None, :], h.shape[:2]
        )
        (h, _), _ = jax.lax.scan(one_layer, (h, positions), local_layers)
        return h

    return stage_fn


def llama_pipeline_forward(
    config,
    params: Any,
    input_ids: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    n_microbatches: Optional[int] = None,
    virtual_stages: Optional[int] = None,
) -> jax.Array:
    """Pipelined equivalent of ``LlamaForCausalLM.apply`` (logits).

    Requires ``config.scan_layers=True`` — the stacked block weights ARE the
    pipeline stages.
    """
    from ..models.llama import rms_norm

    if not config.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True (stacked blocks)")
    model_p = params["model"] if "model" in params else params
    stacked = model_p["layers"]["block"]

    embed = model_p["embed_tokens"]["embedding"]
    x = jnp.take(embed, input_ids, axis=0).astype(config.dtype)

    x = pipeline_apply(
        _llama_stage_fn(config), stacked, x,
        mesh=mesh, n_microbatches=n_microbatches, axis_name="pp",
        virtual_stages=virtual_stages,
    )

    x = rms_norm(x, model_p["norm"]["weight"].astype(x.dtype), config.rms_norm_eps)
    if config.tie_word_embeddings:
        return x @ embed.T.astype(config.dtype)
    return x @ params["lm_head"]["kernel"].astype(config.dtype)
