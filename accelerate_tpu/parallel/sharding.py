"""Sharding planner: ParallelismConfig + plugins → PartitionSpecs.

This module is where the reference's entire parallelism backend zoo lands
(SURVEY.md §2.9): the FSDP flat-param runtime, DeepSpeed ZeRO stages 1-3, HSDP
and DDP all reduce to *which mesh axes each tensor class is sharded over*:

  ===============  ==========================  =========================
  strategy         params                      grads / optimizer state
  ===============  ==========================  =========================
  DDP/NO_SHARD     replicated                  replicated (psum'd grads)
  ZeRO-1           replicated                  opt state over dp_shard
  ZeRO-2/SHARD_    replicated                  grads+opt over dp_shard
  GRAD_OP
  ZeRO-3/FULL_     largest dim over dp_shard   same spec as params
  SHARD (FSDP)     (joined with cp)
  HSDP             shard over dp_shard,        same
                   replicate over dp_replicate
  TP               rule-table name→spec        follows params
  ===============  ==========================  =========================

XLA's SPMD partitioner then materializes the FSDP all-gather on use /
reduce-scatter on grads that the reference implements by hand in
``utils/fsdp_utils.py:645-807`` — with the weight-update sharding trick from
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel Training"
(arXiv:2004.13336) falling out for free.
"""

from __future__ import annotations

import logging
import math
import re
from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)

P = PartitionSpec


def named_sharding(mesh: Mesh, spec: PartitionSpec) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _axis_capacity(mesh: Mesh, axes: tuple[str, ...]) -> int:
    n = 1
    for ax in axes:
        n *= mesh.shape[ax]
    return n


def batch_partition_spec(ndim: int, parallelism_config=None, *, seq_dim: int = 1) -> PartitionSpec:
    """Spec for an input batch leaf: dim 0 over the data axes, the sequence
    dim over cp/sp when active (reference: data-parallel ranks each read their
    own shard, data_loader.py:1014; cp/sp shard the sequence,
    SURVEY.md §2.3)."""
    from ..parallelism_config import ParallelismConfig

    cfg = parallelism_config or ParallelismConfig()
    entries: list = [None] * ndim
    if ndim >= 1:
        entries[0] = cfg.batch_axes
    if ndim > seq_dim and (cfg.cp_size > 1 or cfg.sp_size > 1):
        entries[seq_dim] = tuple(ax for ax in cfg.seq_axes if cfg.axis_size(ax) > 1)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _path_to_name(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def fsdp_spec_for_leaf(
    shape: tuple[int, ...],
    fsdp_axes: tuple[str, ...],
    mesh: Mesh,
    min_size_to_shard: int = 2**11,
) -> PartitionSpec:
    """FULL_SHARD spec for one param: shard the *largest divisible* dim over
    the fsdp axes (the per-param analog of FSDP2's ``fully_shard``; dim choice
    maximizes balance, matching XLA's preference for sharding the contracting
    or output dim of large matmuls).

    Small params stay replicated — the analog of the reference's auto-wrap
    ``min_num_params`` carve-out (utils/dataclasses.py:1584-2190)."""
    n_shards = _axis_capacity(mesh, fsdp_axes)
    if n_shards == 1 or math.prod(shape) < min_size_to_shard:
        return P()
    # Prefer the largest dim that divides evenly; ties → later dim (output
    # features), which keeps embedding tables sharded on vocab.
    best_dim, best_size = None, 0
    for d, s in enumerate(shape):
        if s % n_shards == 0 and s >= best_size:
            best_dim, best_size = d, s
    if best_dim is None:
        return P()
    entries: list = [None] * len(shape)
    entries[best_dim] = tuple(fsdp_axes) if len(fsdp_axes) > 1 else fsdp_axes[0]
    return P(*entries)


def plan_parameter_sharding(
    params: Any,
    mesh: Mesh,
    *,
    fsdp_plugin=None,
    parallelism_config=None,
    tp_rules: Optional[list[tuple[str, PartitionSpec]]] = None,
    min_size_to_shard: Optional[int] = None,
    shards_params_override: Optional[bool] = None,
) -> Any:
    """Return a pytree of :class:`NamedSharding` matching ``params``.

    Precedence per leaf: explicit TP rule (regex on the "/"-joined param path)
    → FSDP policy → replicated. TP rules compose with FSDP: a TP'd dim stays
    TP'd and FSDP shards a *different* dim when one divides evenly.

    ``shards_params_override`` forces the shard-over-dp_shard decision
    regardless of the plugin's strategy — ZeRO-1/2 (SHARD_GRAD_OP) uses it to
    plan *optimizer-state* shardings while the params themselves stay
    replicated. Plugin ``ignored_params`` regexes always win (replicated)."""
    from ..parallelism_config import ParallelismConfig
    from ..utils.dataclasses import FullyShardedDataParallelPlugin

    cfg = parallelism_config or ParallelismConfig()
    tp_rules = tp_rules or []
    ignored_res = [
        re.compile(p) for p in (getattr(fsdp_plugin, "ignored_params", None) or [])
    ]
    # Pipeline stages: stacked scanned-layer weights (leading dim = layer) are
    # sharded over ``pp`` so each stage holds its contiguous L/pp layers
    # locally (parallel/pp.py hands shard_map exactly that slice). The mesh is
    # the source of truth for the axis size — cfg may be defaulted.
    pp_size = mesh.shape.get("pp", 1)
    # Scan-container module names across the families: "layers" everywhere
    # except GPT-2's HF-parity "h" (transformer/h/block/...).
    scan_layer_re = re.compile(r"(^|/)(layers|h)/")
    shards_params = False
    fsdp_axes: tuple[str, ...] = ()
    if fsdp_plugin is not None and fsdp_plugin.shards_params:
        shards_params = True
        fsdp_axes = tuple(ax for ax in cfg.fsdp_axes if mesh.shape[ax] > 1)
    elif fsdp_plugin is None and cfg.dp_shard_size > 1:
        # dp_shard axis active without an explicit plugin → FULL_SHARD default.
        shards_params = True
        fsdp_axes = tuple(ax for ax in cfg.fsdp_axes if mesh.shape[ax] > 1)
    if shards_params_override is not None:
        shards_params = shards_params_override
        fsdp_axes = tuple(ax for ax in cfg.fsdp_axes if mesh.shape[ax] > 1)
    if min_size_to_shard is None:
        min_size_to_shard = (
            fsdp_plugin.min_weight_size_to_shard if fsdp_plugin is not None else 2**11
        )

    def _spec_for(path, leaf) -> NamedSharding:
        if leaf is None or not hasattr(leaf, "shape"):
            return replicated(mesh)
        name = _path_to_name(path)
        if any(r.search(name) for r in ignored_res):
            # Reference: FSDP ignored_modules/params stay unsharded
            # (utils/dataclasses.py:1584-2190).
            return replicated(mesh)
        spec_entries: list = [None] * len(leaf.shape)
        matched_tp = False
        for pattern, spec in tp_rules:
            if re.search(pattern, name):
                spec_entries = list(spec) + [None] * (len(leaf.shape) - len(spec))
                # Divisibility guard: a dim that doesn't divide by its axis
                # capacity falls back to replication on that dim (e.g. GQA
                # kv-heads < tp degree — same fallback transformers' tp_plan
                # applies).
                for d, entry in enumerate(spec_entries):
                    if entry is None:
                        continue
                    axes = entry if isinstance(entry, tuple) else (entry,)
                    if leaf.shape[d] % _axis_capacity(mesh, axes) != 0:
                        logger.warning(
                            "TP rule %s: dim %d of %s (size %d) not divisible by "
                            "axis %s — replicating that dim.",
                            pattern, d, name, leaf.shape[d], entry,
                        )
                        spec_entries[d] = None
                matched_tp = True
                break
        if (
            pp_size > 1
            and spec_entries
            and spec_entries[0] is None
            and scan_layer_re.search(name)
            and leaf.shape[0] % pp_size == 0
        ):
            spec_entries[0] = "pp"
        if shards_params and fsdp_axes:
            used_axes = {a for e in spec_entries if e for a in (e if isinstance(e, tuple) else (e,))}
            free_fsdp = tuple(a for a in fsdp_axes if a not in used_axes)
            # Rank-1 params (norm scales, biases) stay replicated regardless
            # of size: sharding a vector over dp_shard saves ~nothing but
            # lets shardy propagate feature-dim sharding into every
            # activation that touches it — the root cause of the HSDP
            # involuntary-full-remat (see models/llama.py
            # _pin_last_dim_replicated). Stacked scan layouts make norm
            # scales rank-2 (L, H); the leading layer dim is sharded by pp
            # above, never by fsdp, so exclude those too when the feature
            # dim is all that's left.
            rank1_like = len(leaf.shape) < 2 or (
                scan_layer_re.search(name) and len(leaf.shape) == 2
            )
            if free_fsdp and not rank1_like and math.prod(leaf.shape) >= min_size_to_shard:
                n_shards = _axis_capacity(mesh, free_fsdp)
                best_dim, best_size = None, 0
                for d, s in enumerate(leaf.shape):
                    if spec_entries[d] is None and s % n_shards == 0 and s >= best_size:
                        best_dim, best_size = d, s
                if best_dim is not None:
                    spec_entries[best_dim] = free_fsdp if len(free_fsdp) > 1 else free_fsdp[0]
        while spec_entries and spec_entries[-1] is None:
            spec_entries.pop()
        return NamedSharding(mesh, P(*spec_entries))

    return jax.tree_util.tree_map_with_path(_spec_for, params)


def infer_opt_state_sharding(
    opt_state_shapes: Any,
    params: Any,
    param_shardings: Any,
    mesh: Mesh,
    *,
    memory_kind: Optional[str] = None,
) -> Any:
    """Sharding for optimizer state: any leaf whose shape matches a param's
    inherits that param's sharding from ``param_shardings`` (Adam moments etc.);
    everything else (counts, scalars) is replicated.

    ZeRO-1/2 passes a *sharded* plan here while the params themselves stay
    replicated (see Accelerator._prepare_state). ``memory_kind`` pins the
    params-shaped leaves to another memory space — ``"pinned_host"`` is the
    TPU-native FSDP ``cpu_offload`` (the XLA host-offload path replaces the
    reference's CPUOffload wrapper).

    Leaf matching is structural: optax states embed params-shaped subtrees
    (``ScaleByAdamState.mu/nu``), so we walk the state tree and pattern-match
    subtree structure against the param tree."""
    param_leaves = jax.tree_util.tree_leaves(params)
    sharding_leaves = jax.tree_util.tree_leaves(
        param_shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
    )
    if memory_kind is not None:
        sharding_leaves = [s.with_memory_kind(memory_kind) for s in sharding_leaves]
    param_treedef = jax.tree_util.tree_structure(params)

    def _shard_state_leaf(leaf):
        return replicated(mesh)

    def _match(node):
        # A node "is params-shaped" when it has the same treedef as params.
        try:
            if jax.tree_util.tree_structure(node) == param_treedef:
                leaves = jax.tree_util.tree_leaves(node)
                if all(
                    hasattr(l, "shape") and tuple(l.shape) == tuple(p.shape)
                    for l, p in zip(leaves, param_leaves)
                ):
                    return jax.tree_util.tree_unflatten(param_treedef, sharding_leaves)
        except Exception:
            pass
        return None

    def _walk(node):
        matched = _match(node)
        if matched is not None:
            return matched
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple state
            return type(node)(*(_walk(c) for c in node))
        if isinstance(node, (tuple, list)):
            return type(node)(_walk(c) for c in node)
        if isinstance(node, dict):
            return {k: _walk(v) for k, v in node.items()}
        return _shard_state_leaf(node)

    return _walk(opt_state_shapes)


def shard_pytree(tree: Any, shardings: Any):
    """Device-put every leaf with its planned sharding (host → mesh)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s) if hasattr(x, "shape") or np.isscalar(x) else x,
        tree,
        shardings,
    )
