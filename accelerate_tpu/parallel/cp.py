"""Context parallelism: ring attention over the ``cp`` mesh axis.

The reference reaches CP through torch's experimental DTensor
``context_parallel`` (reference: accelerator.py:1658-1671, 4110-4175;
rotation method allgather|alltoall). TPU-native design: sequences are sharded
over the ``cp`` axis by the batch PartitionSpec; attention runs under
``shard_map``, rotating KV chunks around the ring with ``ppermute`` while
accumulating online-softmax partials — compute overlaps the ICI transfer of
the next chunk, HBM stays O(S/cp) per chip. ``allgather`` mode gathers full
KV once instead (cheaper at small cp, reference's default).

Causal masking is handled by chunk offsets: query chunk i attends key chunk j
fully when j < i, causally when j == i, not at all when j > i.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.flash_attention import (
    attention_stats,
    finalize_attention_stats,
    merge_attention_stats,
)


def _mesh_and_cfg():
    from ..state import AcceleratorState

    state = AcceleratorState()
    return state.mesh, state.parallelism_config


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mesh=None,
    rotate_method: Optional[str] = None,
    axis_name: str = "cp",
):
    """Sequence-parallel attention over the ``cp`` axis.

    q/k/v: (B, S, H, D) global arrays with S sharded over ``cp``. Falls back
    to single-chunk attention when the cp axis is trivial.
    """
    cfg = None
    if mesh is None:
        mesh, cfg = _mesh_and_cfg()
    if rotate_method is None:
        rotate_method = getattr(cfg, "cp_rotate_method", None) or "alltoall"
    cp = mesh.shape[axis_name]
    if cp == 1:
        stats = attention_stats(q, k, v, causal=causal)
        return finalize_attention_stats(stats, q.dtype)

    # Manual SPMD region: batch over dp axes, seq over cp, heads over tp/sp.
    qkv_spec = P(("dp_replicate", "dp_shard"), axis_name, "tp", None)

    def _local(q_c, k_c, v_c):
        idx = jax.lax.axis_index(axis_name)
        s_local = q_c.shape[1]
        q_off = idx * s_local

        if rotate_method == "allgather":
            k_all = jax.lax.all_gather(k_c, axis_name, axis=1, tiled=True)
            v_all = jax.lax.all_gather(v_c, axis_name, axis=1, tiled=True)
            stats = attention_stats(q_c, k_all, v_all, causal=causal, q_offset=q_off, k_offset=0)
            return finalize_attention_stats(stats, q_c.dtype)

        # Ring: hold q, rotate kv. After ``step`` rotations this device holds
        # the kv chunk originally owned by (idx - step) % cp.
        def one_step(step, carry):
            stats, k_cur, v_cur = carry
            src = (idx - step) % cp
            new = attention_stats(
                q_c, k_cur, v_cur, causal=causal, q_offset=q_off, k_offset=src * s_local
            )
            stats = merge_attention_stats(stats, new)
            perm = [(i, (i + 1) % cp) for i in range(cp)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return stats, k_nxt, v_nxt

        b, s, h, d = q_c.shape
        init = (
            (
                jnp.zeros((b, h, s, d), jnp.float32),
                jnp.full((b, h, s), -1e30, jnp.float32),
                jnp.zeros((b, h, s), jnp.float32),
            ),
            k_c,
            v_c,
        )
        carry = init
        for step in range(cp):  # cp is static & small: unrolled ring
            carry = one_step(step, carry)
        stats, _, _ = carry
        return finalize_attention_stats(stats, q_c.dtype)

    shard = jax.shard_map(
        _local,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return shard(q, k, v)
