"""Context parallelism: ring attention over the ``cp`` mesh axis.

The reference reaches CP through torch's experimental DTensor
``context_parallel`` (reference: accelerator.py:1658-1671, 4110-4175;
rotation method allgather|alltoall). TPU-native design: sequences are sharded
over the ``cp`` axis by the batch PartitionSpec; attention runs under
``shard_map``, rotating KV chunks around the ring with ``ppermute`` while
accumulating online-softmax partials — compute overlaps the ICI transfer of
the next chunk, HBM stays O(S/cp) per chip. ``allgather`` mode gathers full
KV once instead (cheaper at small cp, reference's default).

Causal masking is handled by chunk offsets: query chunk i attends key chunk j
fully when j < i, causally when j == i, not at all when j > i.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.flash_attention import attention_stats, auto_flash_attention
from ..ops.pallas_flash import (
    default_interpret,
    merge_flash_chunks,
    pallas_flash_attention_with_lse,
)


def _chunk_attention_with_lse(q_c, k_c, v_c, *, causal, q_offset, k_offset):
    """One KV-chunk attention returning (out (B,S,H,D), lse (B,H,S)).

    Pallas fused kernel on TPU (offsets ride scalar prefetch); the
    attention_stats jnp path elsewhere. Both are exact online-softmax partials
    that :func:`merge_flash_chunks` combines across ring rotations.
    """
    if not default_interpret():
        return pallas_flash_attention_with_lse(
            q_c, k_c, v_c, causal=causal, q_offset=q_offset, k_offset=k_offset
        )
    acc, m, l = attention_stats(
        q_c, k_c, v_c, causal=causal, q_offset=q_offset, k_offset=k_offset
    )
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe[..., None]).transpose(0, 2, 1, 3)  # (B, S, H, D) f32
    return out, m + jnp.log(l_safe)


def _mesh_and_cfg():
    from ..state import AcceleratorState

    state = AcceleratorState()
    return state.mesh, state.parallelism_config


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mesh=None,
    rotate_method: Optional[str] = None,
    axis_name: str = "cp",
    batch_axes: Optional[tuple] = ("dp_replicate", "dp_shard"),
):
    """Sequence-parallel attention over the ``cp`` axis.

    q/k/v: (B, S, H, D) global arrays with S sharded over ``cp``. Falls back
    to single-chunk attention when the cp axis is trivial.
    """
    cfg = None
    if mesh is None:
        mesh, cfg = _mesh_and_cfg()
    if rotate_method is None:
        rotate_method = getattr(cfg, "cp_rotate_method", None) or "alltoall"
    cp = mesh.shape[axis_name]
    if cp == 1:
        # Global (non-manual) context: auto_flash_attention adds the
        # shard_map a Mosaic kernel needs under a multi-device mesh.
        return auto_flash_attention(q, k, v, causal=causal, mesh=mesh)

    # Manual SPMD region: batch over dp axes (or replicated — generation's
    # small batches pass batch_axes=()), seq over cp, heads over tp/sp.
    qkv_spec = P(batch_axes if batch_axes else None, axis_name, "tp", None)

    def _local(q_c, k_c, v_c):
        idx = jax.lax.axis_index(axis_name)
        s_local = q_c.shape[1]
        q_off = idx * s_local

        if rotate_method == "allgather":
            k_all = jax.lax.all_gather(k_c, axis_name, axis=1, tiled=True)
            v_all = jax.lax.all_gather(v_c, axis_name, axis=1, tiled=True)
            out, _ = _chunk_attention_with_lse(
                q_c, k_all, v_all, causal=causal, q_offset=q_off, k_offset=0
            )
            return out.astype(q_c.dtype)

        # Ring: hold q, rotate kv. After ``step`` rotations this device holds
        # the kv chunk originally owned by (idx - step) % cp. Chunk partials
        # (out, lse) merge exactly via logsumexp weights; XLA overlaps the
        # ppermute of the next chunk with the current chunk's kernel.
        def one_step(step, carry):
            out, lse, k_cur, v_cur = carry
            src = (idx - step) % cp
            o_i, lse_i = _chunk_attention_with_lse(
                q_c, k_cur, v_cur, causal=causal, q_offset=q_off,
                k_offset=src * s_local,
            )
            out, lse = merge_flash_chunks(out, lse, o_i.astype(jnp.float32), lse_i)
            perm = [(i, (i + 1) % cp) for i in range(cp)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return out, lse, k_nxt, v_nxt

        b, s, h, d = q_c.shape
        carry = (
            jnp.zeros((b, s, h, d), jnp.float32),
            jnp.full((b, h, s), -1e30, jnp.float32),
            k_c,
            v_c,
        )
        for step in range(cp):  # cp is static & small: unrolled ring
            carry = one_step(step, carry)
        return carry[0].astype(q_c.dtype)

    from ..utils.environment import shard_map_compat

    shard = shard_map_compat(
        _local,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return shard(q, k, v)
