from .sharding import (
    batch_partition_spec,
    infer_opt_state_sharding,
    plan_parameter_sharding,
    named_sharding,
    replicated,
)
from .pp import llama_pipeline_forward, pipeline_apply
