"""Fleet router — cell-granular failover above the serving engines.

One engine on one mesh is a CELL, not a fleet: every robustness guarantee
below this layer (admission SLOs, deterministic chaos, the request
journal's exactly-once ``recover()``, SDC quarantine, autoscale resize)
stops at the boundary of a single :class:`~accelerate_tpu.serving.
ServingEngine` — a whole-cell loss loses every in-flight request in it.
The :class:`FleetRouter` treats whole engines as schedulable units the way
arXiv:2412.14374 treats per-stage programs as independently schedulable /
restartable units, in four legs:

1. **Cell registry + health.** Each cell is a JOURNALED engine with its own
   WAL directory, weights version, and rolling ``window_stats()``. The
   router heartbeats cells every tick and classifies them
   ``healthy | degraded | draining | dead`` — a cell that stops making
   progress with work pending for ``FleetConfig.max_idle_ticks`` ticks (the
   engine-level hang guard's definition, fleet-scoped) or whose process
   exits per ``EXIT_CODE_TABLE`` is dead. Every routing decision is a pure
   function of (tick, registry state, request key), so seeded runs replay
   bit-identically — the same counter-based determinism discipline as
   chaos.py.

2. **Routing + spillover.** ``submit()`` picks a cell by session-affinity
   hash (the seam prefix-affinity routing will plug into), spilling to the
   least-loaded cell when the affinity target's queue-depth p95 breaches
   ``FleetConfig.queue_depth_band``. The router sheds only when ALL cells
   breach — and SLO aggregates stay per-cell (unweighted across cells), so
   one sick cell can't hide behind a big healthy one's volume.

3. **Exactly-once cross-cell drain.** When a cell dies mid-trace the
   router ADOPTS the dead cell's journal directory (journal.py's sentinel
   arbitrates against a restarting cell supervisor — double adoption is
   double execution) and replays it: journaled terminals re-emit their
   cached rows, never re-executed; in-flight requests resubmit by
   ``client_request_id`` onto surviving cells — a recovery, so they never
   spend ``max_retries`` — and their deadlines re-anchor charging
   pre-crash runtime but not the outage (the journal's monotonic
   ``t_mono`` stamps). Under equal weights the replayed rows are bit-equal
   to an uninterrupted run: zero lost, zero double-executed.

4. **Cell-granular lifecycle.** ``publish()`` canaries a whole CELL (the
   canary cell binds the candidate at ``fraction=1.0`` via the engine's
   existing canary machinery — the same seam ``WeightPublisher`` drives);
   after ``canary_ticks`` the fleet-level SLO comparison decides
   promote-all (``swap_params`` on every other live cell) or rollback +
   quarantine-the-version (``publish()`` refuses it thereafter).
   ``scale_up()/scale_down()`` spin an ENTIRE cell up or down through the
   existing planner-validated engine construction path rather than
   resizing one mesh.

Deterministic chaos points (chaos.py): ``cell_crash`` hard-kills a cell
mid-trace (the drain path's game day), ``cell_partition`` makes a cell
unreachable for ``extra["delay_ticks"]`` ticks (it keeps ticking; its rows
surface on heal), ``router_heartbeat`` skips one health pass.

Off by default: nothing constructs a router unless you do —
``Accelerator.build_fleet_router`` or this module directly. ``make
fleet-smoke`` is the game-day gate.

Usage::

    from accelerate_tpu import FleetConfig, FleetRouter

    router = FleetRouter({"cell0": engine0, "cell1": engine1},
                         FleetConfig(), chaos=injector)
    rid = router.submit(prompt, client_request_id="req-0",
                        session_id="sess-7")
    while router.pending:
        router.tick()
        for row in router.poll():
            ...   # row["cell"], row["spilled"], row["drained_from"]
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .journal import RequestJournal
from .logging import get_logger
from .utils.constants import (
    CELL_DEAD_EXIT_CODE,
    FLEET_DEGRADED_EXIT_CODE,
)

logger = get_logger(__name__)

__all__ = ["FleetConfig", "FleetRouter", "FleetDegradedError", "CELL_STATES"]

#: Legal cell health classifications, healthiest first.
CELL_STATES = ("healthy", "degraded", "draining", "dead")

# Default partition length (router ticks) when a cell_partition schedule
# entry carries no ``delay_ticks``.
_DEFAULT_PARTITION_TICKS = 2


def _log_ok() -> bool:
    from .state import PartialState

    return bool(PartialState._shared_state)


class FleetDegradedError(RuntimeError):
    """No healthy cell remains to route or drain onto. Front-ends exit
    ``FLEET_DEGRADED_EXIT_CODE`` (81): more capacity — not a faster
    restart — is the fix, so the supervisor relaunches WITH backoff."""

    exit_code = FLEET_DEGRADED_EXIT_CODE


_MASK = (1 << 64) - 1


def _affinity_hash(key: str) -> int:
    """Session-affinity hash: crc32 -> splitmix64 finalizer. Deterministic
    across processes and platforms (never Python's randomized ``hash``),
    so a seeded run routes identically on replay."""
    x = (zlib.crc32(str(key).encode("utf-8")) + 0x9E3779B97F4A7C15) & _MASK
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return (z ^ (z >> 31)) & _MASK


@dataclass
class FleetConfig:
    """Fleet-router knobs.

    - ``max_idle_ticks`` — a cell with work pending whose progress marker
      holds still this many consecutive router ticks is classified dead
      (and drained).
    - ``queue_depth_band`` — a cell whose rolling queue-depth p95 exceeds
      this spills new admissions to the least-loaded in-band cell; when
      EVERY cell breaches, the router sheds.
    - ``canary_ticks`` — minimum router ticks a cell-granular publish
      canary window lasts before the promote/rollback decision.
    - ``min_canary_cohort`` — minimum terminal events the canary cell's
      cohort needs before the decision (first-dispatch noise must not
      decide a rollback).
    - ``slo_tolerance`` — the canary cell's ok-ratio may trail the fleet
      baseline by this much and still promote.
    """

    max_idle_ticks: int = 8
    queue_depth_band: float = 16.0
    canary_ticks: int = 8
    min_canary_cohort: int = 4
    slo_tolerance: float = 0.05

    def __post_init__(self):
        if int(self.max_idle_ticks) < 1:
            raise ValueError(
                f"max_idle_ticks must be >= 1, got {self.max_idle_ticks}")
        if float(self.queue_depth_band) <= 0.0:
            raise ValueError(
                f"queue_depth_band must be > 0, got {self.queue_depth_band}")
        if int(self.canary_ticks) < 1:
            raise ValueError(
                f"canary_ticks must be >= 1, got {self.canary_ticks}")
        if int(self.min_canary_cohort) < 1:
            raise ValueError(
                f"min_canary_cohort must be >= 1, got {self.min_canary_cohort}")
        if not 0.0 <= float(self.slo_tolerance) < 1.0:
            raise ValueError(
                f"slo_tolerance must be in [0, 1), got {self.slo_tolerance}")


class _Cell:
    """One registered engine plus the router's health bookkeeping for it."""

    __slots__ = ("name", "index", "engine", "journal_dir", "draining",
                 "dead", "death_class", "died_tick", "partitioned_until",
                 "last_marker", "idle_ticks", "pad_token_id")

    def __init__(self, name: str, index: int, engine):
        self.name = name
        self.index = index
        self.engine = engine
        self.journal_dir = engine.journal.dir
        self.draining = False
        self.dead = False
        self.death_class: Optional[str] = None
        self.died_tick: Optional[int] = None
        self.partitioned_until = -1
        self.last_marker = None
        self.idle_ticks = 0
        self.pad_token_id = int(engine.pad_token_id)

    def state(self, tick: int) -> str:
        if self.dead:
            return "dead"
        if self.draining:
            return "draining"
        if self.partitioned_until > tick:
            return "degraded"
        return "healthy"


class FleetRouter:
    """Session-affinity router + health/failover control plane over a
    registry of journaled serving cells. See the module docstring for the
    four legs; every public method is host-side bookkeeping — the router
    never touches device state, so the per-cell zero-recompile invariant
    (one decode executable, zero steady recompiles) is untouched.

    ``cells`` is a ``{name: engine}`` mapping or a list of engines
    (auto-named ``cell0..cellN``); every engine must have a journal
    attached — a cell without a WAL cannot be drained, which defeats the
    point of a fleet."""

    def __init__(self, cells, config: Optional[FleetConfig] = None, *,
                 chaos=None, telemetry=None, tracing=None):
        self.config = config if config is not None else FleetConfig()
        self.chaos = chaos
        self.telemetry = telemetry
        self.tracing = tracing
        if not isinstance(cells, dict):
            cells = {f"cell{i}": eng for i, eng in enumerate(cells)}
        if not cells:
            raise ValueError("a fleet needs at least one cell")
        self._cells: dict[str, _Cell] = {}
        for name, engine in cells.items():
            self._register(str(name), engine)
        self._ticks = 0
        self._next_rid = 0
        # Router-level request book: rid -> routing record; cid -> rid for
        # idempotency; (cell, engine rid) -> rid for poll translation.
        self._requests: dict[int, dict] = {}
        self._cids: dict[str, int] = {}
        self._by_cell: dict[tuple[str, int], int] = {}
        self._rows: dict[int, dict] = {}
        self._finished: list[dict] = []
        # Journals this router adopted from dead cells. Held until close():
        # a relaunched cell supervisor must start a FRESH journal dir — its
        # old requests already live on the survivors.
        self._adopted: list[RequestJournal] = []
        self._publish: Optional[dict] = None
        self._quarantined: set[int] = set()
        self._c = {
            "submitted": 0, "deduped": 0, "routed_affinity": 0,
            "routed_spilled": 0, "shed": 0, "completed": 0, "ok": 0,
            "drains": 0, "drained_cached": 0, "drained_resubmitted": 0,
            "publishes": 0, "promoted": 0, "rolled_back": 0,
            "scale_ups": 0, "scale_downs": 0, "heartbeat_skips": 0,
        }
        self._drain_last_s: Optional[float] = None
        if self.tracing is not None:
            self.tracing.register_gauges("fleet", self.stats)
        self._hub = (getattr(self.tracing, "hub", None)
                     or getattr(self.telemetry, "hub", None))
        if self._hub is not None:
            if self.tracing is None:
                self._hub.register_provider("fleet", self.stats,
                                            replace=True)
            self._hub.register_slo("fleet_availability", 0.99)

    def _register(self, name: str, engine) -> None:
        if name in self._cells:
            raise ValueError(f"cell {name!r} is already registered")
        if engine.journal is None:
            raise ValueError(
                f"cell {name!r} has no journal attached — set "
                "ServingConfig.journal_dir (one directory per cell); an "
                "unjournaled cell cannot be drained after a crash"
            )
        self._cells[name] = _Cell(name, len(self._cells), engine)

    # -- registry views ----------------------------------------------------

    def cell_states(self) -> dict[str, str]:
        """``{name: healthy|degraded|draining|dead}`` right now."""
        return {n: c.state(self._ticks) for n, c in sorted(self._cells.items())}

    def _routable(self) -> list[_Cell]:
        """Cells eligible for NEW admissions, in deterministic name order:
        healthy only — degraded (partitioned) cells are unreachable,
        draining cells are on their way out, dead cells are gone."""
        return [c for _, c in sorted(self._cells.items())
                if c.state(self._ticks) == "healthy"]

    def _alive(self) -> list[_Cell]:
        return [c for _, c in sorted(self._cells.items()) if not c.dead]

    @property
    def pending(self) -> int:
        """Router-level requests not yet terminally resolved."""
        return sum(1 for rid in self._requests if rid not in self._rows)

    # -- leg 2: routing + spillover ---------------------------------------

    def _breaches(self, cell: _Cell) -> bool:
        qd = cell.engine.window_stats()["queue_depth_p95"]
        return qd is not None and qd > float(self.config.queue_depth_band)

    def _route(self, key: str) -> tuple[Optional[_Cell], bool]:
        """The tick-deterministic routing decision: (cell, spilled) — or
        ``(None, False)`` when every routable cell breaches its band (the
        caller sheds). Affinity first; spillover to the least-loaded
        in-band cell only when the affinity target breaches."""
        routable = self._routable()
        if not routable:
            raise FleetDegradedError(
                "no healthy cell to route onto — "
                f"states: {self.cell_states()}"
            )
        target = routable[_affinity_hash(key) % len(routable)]
        if not self._breaches(target):
            return target, False
        in_band = [c for c in routable if c is not target
                   and not self._breaches(c)]
        if not in_band:
            return None, False
        # Least-loaded by the same deterministic signal the breach test
        # reads (queue-depth p95 is integer per-tick samples, never a
        # wall-clock latency), name-tiebroken.
        spill = min(in_band, key=lambda c: (
            c.engine.window_stats()["queue_depth_p95"] or 0.0, c.name))
        return spill, True

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               rng: Optional[jax.Array] = None,
               deadline_s: Optional[float] = None,
               client_request_id: Optional[str] = None,
               session_id: Optional[str] = None) -> int:
        """Route one request onto a cell; returns a ROUTER-level id whose
        row lands in :meth:`poll` with ``cell``/``spilled``/
        ``drained_from`` provenance on top of the engine row. ``session_id``
        pins the affinity hash (defaults to ``client_request_id``, then the
        router id — so anonymous requests still spread deterministically).
        Duplicate ``client_request_id`` submits dedupe fleet-wide, even
        when the original landed on a cell that has since died."""
        cid = (str(client_request_id)
               if client_request_id is not None else None)
        if cid is not None and cid in self._cids:
            self._c["deduped"] += 1
            rid = self._cids[cid]
            row = self._rows.get(rid)
            if row is not None:
                self._finished.append(dict(row))
            return rid
        rid = self._next_rid
        self._next_rid += 1
        # The engine-level idempotency key: ALWAYS set, so a dead cell's
        # journal can name its in-flight requests for cross-cell resubmit.
        eng_cid = cid if cid is not None else f"fleet-{rid}"
        key = session_id if session_id is not None else eng_cid
        cell, spilled = self._route(str(key))
        self._c["submitted"] += 1
        if cell is None:
            # Every cell breaches: shed at the router, poll-row shaped like
            # an engine shed (prompt + pad to budget) so callers see ONE
            # row schema.
            self._c["shed"] += 1
            prompt_arr = np.asarray(prompt, np.int32).reshape(-1)
            budget = int(max_new_tokens) if max_new_tokens is not None else 0
            pad = self._alive()[0].pad_token_id if self._alive() else 0
            row = {
                "id": rid, "status": "shed",
                "tokens": np.concatenate([
                    prompt_arr,
                    np.full((budget,), pad, np.int32)]),
                "new_tokens": 0, "ttft_s": None, "tpot_s": None,
                "weights_version": None, "attempt": 1, "recovered": False,
                "drafted": 0, "accepted": 0,
                "cell": None, "spilled": False, "drained_from": None,
            }
            self._requests[rid] = {"cid": eng_cid, "cell": None,
                                   "eng_rid": None, "spilled": False,
                                   "drained_from": None, "session": str(key)}
            if cid is not None:
                self._cids[cid] = rid
            self._rows[rid] = row
            self._finished.append(dict(row))
            self._c["completed"] += 1
            return rid
        eng_rid = cell.engine.submit(
            prompt, max_new_tokens=max_new_tokens, rng=rng,
            deadline_s=deadline_s, client_request_id=eng_cid)
        self._c["routed_spilled" if spilled else "routed_affinity"] += 1
        self._requests[rid] = {"cid": eng_cid, "cell": cell.name,
                               "eng_rid": eng_rid, "spilled": spilled,
                               "drained_from": None, "session": str(key)}
        self._cids[eng_cid] = rid
        self._by_cell[(cell.name, eng_rid)] = rid
        return rid

    # -- the tick loop -----------------------------------------------------

    def tick(self) -> None:
        """One router heartbeat: draw chaos, tick every live cell, collect
        reachable cells' rows, reclassify health (idle-death detection,
        partition healing, drain retirement), and poll any open publish
        window. Deterministic: every decision is a function of the tick
        counter and journaled/windowed state, never wall-clock."""
        t = self._ticks
        ch = self.chaos
        heartbeat_skip = False
        if ch is not None:
            f = ch.draw("router_heartbeat", t)
            if f is not None:
                heartbeat_skip = True
                self._c["heartbeat_skips"] += 1
        for cell in self._alive():
            if ch is None:
                continue
            f = ch.draw("cell_partition", t, unit=cell.index)
            if f is not None:
                ticks = int((f.extra or {}).get(
                    "delay_ticks", _DEFAULT_PARTITION_TICKS))
                cell.partitioned_until = max(cell.partitioned_until,
                                             t + ticks)
                self._event("fleet_cell_partition", cell=cell.name,
                            tick=t, heal_tick=cell.partitioned_until)
            f = ch.draw("cell_crash", t, unit=cell.index)
            if f is not None:
                self._kill_cell(cell, "cell-dead",
                                reason="injected cell_crash")
        for cell in self._alive():
            try:
                cell.engine.tick()
            except Exception as e:  # a cell death must not kill the fleet
                self._kill_cell(cell, "cell-dead",
                                reason=f"engine tick raised: {e}")
        for cell in self._alive():
            if cell.partitioned_until > t:
                continue  # unreachable: its rows surface on heal
            self._collect(cell)
        if not heartbeat_skip:
            self._health_pass(t)
        self._publish_poll()
        self._ticks += 1

    def _collect(self, cell: _Cell) -> None:
        for row in cell.engine.poll():
            rid = self._by_cell.get((cell.name, row["id"]))
            if rid is None:
                continue  # not routed through this router
            rec = self._requests[rid]
            out = dict(row)
            out["id"] = rid
            out["cell"] = cell.name
            out["spilled"] = rec["spilled"]
            out["drained_from"] = rec["drained_from"]
            if rec["drained_from"] is not None:
                out["recovered"] = True
            self._rows[rid] = out
            self._finished.append(dict(out))
            self._c["completed"] += 1
            if out["status"] == "ok":
                self._c["ok"] += 1
            if self._hub is not None:
                self._hub.observe_slo("fleet_availability",
                                      out["status"] == "ok")

    def poll(self) -> list[dict]:
        """Finished rows since the last call — the engine poll-row schema
        plus ``cell`` (where it executed), ``spilled`` (routed off its
        affinity target), ``drained_from`` (the dead cell it was drained
        from, else None)."""
        out = self._finished
        self._finished = []
        return out

    # -- leg 1: health ----------------------------------------------------

    def _health_pass(self, t: int) -> None:
        for cell in self._alive():
            if cell.partitioned_until == t:
                self._event("fleet_cell_healed", cell=cell.name, tick=t)
            marker = cell.engine._progress_marker()
            if cell.engine.pending > 0 and marker == cell.last_marker:
                cell.idle_ticks += 1
            else:
                cell.idle_ticks = 0
            cell.last_marker = marker
            if cell.idle_ticks >= int(self.config.max_idle_ticks):
                self._kill_cell(
                    cell, "cell-dead",
                    reason=f"no progress for {cell.idle_ticks} ticks "
                           f"with {cell.engine.pending} pending")
                continue
            if cell.draining and cell.engine.pending == 0:
                self._retire(cell)

    def _event(self, event: str, **fields) -> None:
        if self.telemetry is not None:
            try:
                self.telemetry.record_event(event, **fields)
            except Exception as e:  # observability must never kill routing
                logger.warning_once(f"fleet: telemetry event failed: {e}")

    # -- leg 3: exactly-once cross-cell drain ------------------------------

    def _kill_cell(self, cell: _Cell, death_class: str, *,
                   reason: str) -> None:
        """Declare a cell dead (``EXIT_CODE_TABLE`` class ``cell-dead``,
        exit code ``CELL_DEAD_EXIT_CODE``) and drain its journal onto the
        survivors. The engine object is ABANDONED, not closed — exactly
        what a process death leaves behind: an unsealed ``.open`` segment
        the journal's replay reads anyway."""
        if cell.dead:
            return
        cell.dead = True
        cell.death_class = death_class
        cell.died_tick = self._ticks
        engine, cell.engine = cell.engine, None
        del engine  # abandoned: no close(), no seal — a crash leaves both
        if _log_ok():
            logger.warning(
                "fleet: cell %r is dead at tick %d (%s, exit class %r "
                "code %d) — draining its journal onto survivors",
                cell.name, self._ticks, reason, death_class,
                CELL_DEAD_EXIT_CODE,
            )
        self._event("fleet_cell_dead", cell=cell.name, tick=self._ticks,
                    reason=reason, exit_code=CELL_DEAD_EXIT_CODE)
        self._drain_dead_cell(cell)

    def _drain_dead_cell(self, cell: _Cell) -> None:
        """Replay the dead cell's journal exactly-once onto the survivors:
        terminals -> cached rows (never re-executed), in-flight -> fresh
        submits by ``client_request_id`` on a surviving cell (a recovery,
        not a retry), deadlines re-anchored to charge pre-crash runtime
        but not the outage."""
        t0 = time.perf_counter()
        tr = self.tracing
        span = (tr.begin("fleet", "drain", self._ticks, cell=cell.name)
                if tr is not None else None)
        try:
            jr = RequestJournal.adopt(
                cell.journal_dir,
                f"fleet-router:tick={self._ticks}:cell={cell.name}")
        except Exception:
            if span is not None:
                tr.end(span, self._ticks, error="adoption refused")
            raise
        try:
            records, scan = jr.replay()
        except Exception:
            jr.release_adoption()
            raise
        self._adopted.append(jr)
        admits: dict[int, dict] = {}
        terminals: dict[int, dict] = {}
        last_mono = None
        for rec in records:
            tm = rec.get("t_mono")
            if tm is not None:
                last_mono = tm if last_mono is None else max(last_mono, tm)
            erid = rec.get("rid")
            if erid is None:
                continue
            erid = int(erid)
            if rec.get("t") == "admit":
                admits[erid] = rec
            elif rec.get("t") == "terminal":
                terminals[erid] = rec
        now = time.perf_counter()
        n_cached = n_resubmitted = 0
        # Union, not just admits: the cell's compactor retires the admit of a
        # finished request (its terminal row is self-contained), so a cached
        # reply can survive on disk with no admit record left.
        for erid in sorted(set(admits) | set(terminals)):
            a = admits.get(erid)
            trec = terminals.get(erid)
            cid = a.get("cid") if a is not None else trec.get("cid")
            rid = self._cids.get(str(cid)) if cid is not None else None
            if rid is None:
                continue  # not routed through this router (e.g. warmup)
            if rid in self._rows:
                continue  # already resolved fleet-side
            rec = self._requests[rid]
            if trec is not None:
                # Journaled terminal: re-emit the cached row, provenance'd.
                row = {
                    "id": rid, "status": trec.get("status"),
                    "tokens": np.asarray(trec.get("row", []), np.int32),
                    "new_tokens": int(trec.get("new_tokens", 0)),
                    "ttft_s": trec.get("ttft_s"),
                    "tpot_s": trec.get("tpot_s"),
                    "weights_version": trec.get("weights_version"),
                    "attempt": int(trec.get("attempt", 1)),
                    "recovered": True,
                    "drafted": int(trec.get("drafted", 0)),
                    "accepted": int(trec.get("accepted", 0)),
                    "cell": cell.name, "spilled": rec["spilled"],
                    "drained_from": cell.name,
                }
                rec["drained_from"] = cell.name
                self._rows[rid] = row
                self._finished.append(dict(row))
                self._c["completed"] += 1
                if row["status"] == "ok":
                    self._c["ok"] += 1
                n_cached += 1
                continue
            # In-flight: resubmit on a surviving cell — same prompt, same
            # per-request rng, same idempotency key, so the replay is
            # bit-equal under equal weights.
            targets = self._routable()
            if not targets:
                for j in self._adopted:
                    j.release_adoption()
                raise FleetDegradedError(
                    f"cell {cell.name!r} died with requests in flight and "
                    "no healthy cell remains to drain onto — states: "
                    f"{self.cell_states()}"
                )
            target = targets[_affinity_hash(rec["session"]) % len(targets)]
            try:
                rng = jax.random.wrap_key_data(
                    jnp.asarray(a["rng"], jnp.uint32))
            except Exception:
                rng = jax.random.key(0)
            dl = a.get("deadline_s")
            remaining = None
            if dl is not None:
                elapsed = 0.0
                if last_mono is not None and a.get("t_mono") is not None:
                    # Pre-crash runtime in the DEAD cell's own monotonic
                    # epoch: charge what it actually ran, not the outage.
                    elapsed = max(0.0, float(last_mono) - float(a["t_mono"]))
                remaining = max(0.0, float(dl) - elapsed)
            new_erid = target.engine.submit(
                np.asarray(a["tokens"], np.int32),
                max_new_tokens=int(a["budget"]), rng=rng,
                deadline_s=remaining, client_request_id=str(cid))
            rec["cell"] = target.name
            rec["eng_rid"] = new_erid
            rec["drained_from"] = cell.name
            self._by_cell[(target.name, new_erid)] = rid
            n_resubmitted += 1
        self._drain_last_s = time.perf_counter() - t0
        self._c["drains"] += 1
        self._c["drained_cached"] += n_cached
        self._c["drained_resubmitted"] += n_resubmitted
        if _log_ok():
            logger.warning(
                "fleet: drained cell %r in %.3fs — %d terminals re-emitted "
                "from cache, %d in-flight resubmitted (%d journal records, "
                "%d segments)", cell.name, self._drain_last_s, n_cached,
                n_resubmitted, scan["records"], scan["segments"],
            )
        self._event("fleet_cell_drained", cell=cell.name,
                    seconds=self._drain_last_s, cached=n_cached,
                    resubmitted=n_resubmitted)
        if span is not None:
            tr.end(span, self._ticks, cached=n_cached,
                   resubmitted=n_resubmitted)

    # -- leg 4: cell-granular lifecycle ------------------------------------

    def publish(self, params, *, weights_version: int) -> dict:
        """Start a CELL-granular canary: the (deterministically chosen)
        canary cell binds every one of its new admissions to the candidate
        (``fraction=1.0`` through the engine's own canary machinery — the
        same seam ``WeightPublisher`` drives for request-granular canaries)
        while the rest of the fleet serves the old version. The decision
        lands in :meth:`tick` after ``canary_ticks``: promote-all, or
        rollback + quarantine the version. A quarantined version is
        refused here ever after."""
        v = int(weights_version)
        if v in self._quarantined:
            raise ValueError(
                f"weights_version {v} is quarantined — a cell canary "
                "rolled it back; publish a new version instead")
        if self._publish is not None:
            raise ValueError(
                f"a fleet publish (version {self._publish['version']}) is "
                "already in flight")
        routable = self._routable()
        if not routable:
            raise FleetDegradedError(
                f"no healthy cell to canary on — states: {self.cell_states()}")
        canary = routable[0]  # deterministic: lowest name
        canary.engine.begin_canary(params, weights_version=v, fraction=1.0)
        self._publish = {"version": v, "cell": canary.name, "params": params,
                         "started_tick": self._ticks}
        self._c["publishes"] += 1
        self._event("fleet_publish_begin", version=v, cell=canary.name,
                    tick=self._ticks)
        return {"version": v, "canary_cell": canary.name}

    def _fleet_baseline_ok(self, exclude: str) -> Optional[float]:
        """Fleet SLO baseline: the UNWEIGHTED mean of per-cell ok-ratios
        over the other live cells' rolling windows — per-cell on purpose,
        so one sick cell counts as one cell instead of hiding under a big
        healthy cell's request volume."""
        ratios = []
        for cell in self._alive():
            if cell.name == exclude:
                continue
            w = cell.engine.window_stats()
            if w["requests"]:
                ratios.append(w["ok"] / w["requests"])
        return sum(ratios) / len(ratios) if ratios else None

    def _publish_poll(self) -> None:
        p = self._publish
        if p is None:
            return
        cell = self._cells.get(p["cell"])
        if cell is None or cell.dead:
            # The canary cell died mid-window: the candidate was never
            # fleet-visible, so just end the window (no quarantine — the
            # VERSION was not convicted, the cell was).
            self._publish = None
            self._c["rolled_back"] += 1
            self._event("fleet_publish_aborted", version=p["version"],
                        cell=p["cell"], tick=self._ticks)
            return
        if self._ticks - p["started_tick"] < int(self.config.canary_ticks):
            return
        co = cell.engine.cohort_stats(p["version"])
        if co is None or co["completed"] < int(self.config.min_canary_cohort):
            return  # keep the window open until the cohort is decidable
        canary_ok = co["ok"] / co["completed"]
        baseline = self._fleet_baseline_ok(exclude=cell.name)
        promote = (baseline is None
                   or canary_ok + float(self.config.slo_tolerance)
                   >= baseline)
        if promote:
            cell.engine.promote_canary()
            for other in self._alive():
                if other.name != cell.name:
                    other.engine.swap_params(
                        p["params"], weights_version=p["version"])
            self._c["promoted"] += 1
            self._event("fleet_publish_promoted", version=p["version"],
                        canary_ok=round(canary_ok, 4),
                        baseline=(round(baseline, 4)
                                  if baseline is not None else None))
        else:
            cell.engine.rollback_canary()
            self._quarantined.add(p["version"])
            self._c["rolled_back"] += 1
            if _log_ok():
                logger.warning(
                    "fleet: version %d rolled back on canary cell %r "
                    "(ok %.3f vs fleet baseline %.3f) — version "
                    "QUARANTINED fleet-wide", p["version"], cell.name,
                    canary_ok, baseline,
                )
            self._event("fleet_publish_rolled_back", version=p["version"],
                        canary_ok=round(canary_ok, 4),
                        baseline=round(baseline, 4))
        self._publish = None

    def scale_up(self, name: str, engine=None, *, factory=None) -> None:
        """Register a whole new cell. Pass a constructed (journaled,
        ideally warmed) engine, or a zero-arg ``factory`` so construction
        — which runs the existing planner-validated
        ``build_serving_engine`` path — happens inside the router's
        accounting."""
        if engine is None:
            if factory is None:
                raise ValueError("scale_up needs an engine or a factory")
            engine = factory()
        self._register(str(name), engine)
        self._c["scale_ups"] += 1
        self._event("fleet_scale_up", cell=str(name), tick=self._ticks)

    def scale_down(self, name: str) -> None:
        """Drain a whole cell out: no new admissions from now on; once its
        in-flight work finishes the engine is closed and deregistered at
        the end of a tick."""
        cell = self._cells.get(str(name))
        if cell is None or cell.dead:
            raise ValueError(f"no live cell named {name!r}")
        cell.draining = True
        self._event("fleet_scale_down", cell=str(name), tick=self._ticks)

    def _retire(self, cell: _Cell) -> None:
        self._collect(cell)  # anything its last tick finished
        cell.engine.close()
        del self._cells[cell.name]
        self._c["scale_downs"] += 1
        self._event("fleet_cell_retired", cell=cell.name, tick=self._ticks)

    # -- reporting / lifecycle --------------------------------------------

    def stats(self) -> dict:
        """The ``fleet`` telemetry block (pinned by tests/test_schemas.py;
        the MetricsHub renders it under ``accelerate_tpu_fleet_*``)."""
        states = self.cell_states()
        per_cell = {}
        for name, cell in sorted(self._cells.items()):
            if cell.dead:
                per_cell[name] = {
                    "state": "dead", "pending": None,
                    "weights_version": None, "queue_depth_p95": None,
                    "requests_completed": None, "decode_executables": None,
                    "steady_recompiles": None,
                }
                continue
            eng = cell.engine
            per_cell[name] = {
                "state": states[name],
                "pending": eng.pending,
                "weights_version": eng.weights_version,
                "queue_depth_p95": eng.window_stats()["queue_depth_p95"],
                "requests_completed": eng._stats["completed"],
                "decode_executables": eng.executable_counts()["decode"],
                "steady_recompiles": eng._stats["steady_recompiles"],
            }
        return {
            "cells": len(self._cells),
            "healthy": sum(1 for s in states.values() if s == "healthy"),
            "degraded": sum(1 for s in states.values() if s == "degraded"),
            "draining": sum(1 for s in states.values() if s == "draining"),
            "dead": sum(1 for s in states.values() if s == "dead"),
            "ticks": self._ticks,
            "submitted": self._c["submitted"],
            "deduped": self._c["deduped"],
            "routed_affinity": self._c["routed_affinity"],
            "routed_spilled": self._c["routed_spilled"],
            "shed": self._c["shed"],
            "completed": self._c["completed"],
            "ok": self._c["ok"],
            "heartbeat_skips": self._c["heartbeat_skips"],
            "drains": self._c["drains"],
            "drained_cached": self._c["drained_cached"],
            "drained_resubmitted": self._c["drained_resubmitted"],
            "drain_last_s": (round(self._drain_last_s, 6)
                             if self._drain_last_s is not None else None),
            "publishes": self._c["publishes"],
            "promoted": self._c["promoted"],
            "rolled_back": self._c["rolled_back"],
            "quarantined_versions": sorted(self._quarantined),
            "scale_ups": self._c["scale_ups"],
            "scale_downs": self._c["scale_downs"],
            "per_cell": per_cell,
        }

    def close(self) -> None:
        """Close every live cell's engine and release the dead cells'
        adopted journals (a relaunching supervisor may take them over
        from here — the drained requests dedupe by their journaled
        ``client_request_id`` terminal rows)."""
        for cell in self._alive():
            cell.engine.close()
        for jr in self._adopted:
            jr.release_adoption()
        self._adopted.clear()
