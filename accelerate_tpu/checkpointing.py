"""Training-state checkpointing (layer L8).

Reference: src/accelerate/checkpointing.py:63-341 + accelerator.py:3584-3748.
Directory contract mirrors the reference: per checkpoint dir —
``model.safetensors`` (fp32 master params, name-keyed), ``optimizer.bin``,
``scheduler.bin``, ``sampler.bin``, ``random_states_<rank>.pkl``, plus
``custom_checkpoint_<i>.pkl`` for registered objects. Param/optimizer identity
is by *name* (flattened "/"-paths), never object id, so checkpoints survive
resharding — load into any mesh shape and every leaf lands back through its
planned NamedSharding (SURVEY.md §7 hard-part 5).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

import jax
import numpy as np

from .logging import get_logger
from .utils.constants import (
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCALER_NAME,
    SCHEDULER_NAME,
)
from .utils.operations import to_global_host
from .utils.other import (
    flatten_state_dict,
    load_sharded_safetensors,
    save_sharded_safetensors,
    unflatten_state_dict,
)
from .utils.random import load_rng_state, rng_state

logger = get_logger(__name__)


def _list_checkpoint_dirs(base: str) -> list[str]:
    """``checkpoint_<N>`` entries under ``base``, sorted by N ascending.

    Non-matching entries — an interrupted ``checkpoint_N.tmp`` staging dir, a
    stray user-created ``checkpoint_tmp`` folder — are skipped with a
    one-time warning instead of the old ``int(f.split("_")[1])`` ValueError
    that crashed both the load resolver and the total_limit pruner."""
    from .fault_tolerance import checkpoint_index

    found = []
    for f in os.listdir(base):
        idx = checkpoint_index(f)
        if idx is None:
            if f.startswith("checkpoint_"):
                logger.warning_once(
                    f"Ignoring non-checkpoint entry {f!r} in {base} (an "
                    "interrupted staging dir or a stray folder)."
                )
            continue
        found.append((idx, f))
    return [f for _, f in sorted(found)]


def _checkpoint_dir(accelerator, output_dir: Optional[str], for_load: bool = False) -> str:
    pc = accelerator.project_configuration
    if pc.automatic_checkpoint_naming and output_dir is None:
        base = os.path.join(accelerator.project_dir or ".", "checkpoints")
        if for_load:
            folders = _list_checkpoint_dirs(base)
            if not folders:
                raise FileNotFoundError(f"No checkpoints found in {base}")
            ft = getattr(accelerator, "fault_tolerance", None)
            if ft is not None and ft.handler.verify_on_load:
                # Newest checkpoint whose manifest verifies; torn ones are
                # logged, counted in telemetry and skipped
                # (fault_tolerance.py).
                chosen = ft.resolve_verified(base, folders)
            else:
                chosen = folders[-1]
            # Continue numbering past the NEWEST existing checkpoint (even a
            # torn one the verified walk skipped) so the next save doesn't
            # clobber anything. This deliberately goes beyond the reference,
            # which never bumps ``iteration`` on load (reference:
            # accelerator.py load_state) and instead errors at save time if
            # the slot already exists. Done here — the single resolution
            # point — because load_state may pre-resolve for its pre-hooks,
            # after which load_accelerator_state sees a non-None input_dir.
            from .fault_tolerance import checkpoint_index

            pc.iteration = checkpoint_index(folders[-1]) + 1
            return os.path.join(base, chosen)
        out = os.path.join(base, f"checkpoint_{pc.iteration}")
        return out
    if output_dir is None:
        raise ValueError("Provide output_dir or enable automatic_checkpoint_naming.")
    return output_dir


def _prune_total_limit(accelerator, base: str, room_for: int) -> None:
    """Drop the oldest checkpoints so ``existing + room_for`` fits
    ``total_limit``. ``room_for=1`` is the legacy pre-save prune (make room
    for the save about to happen); ``room_for=0`` is the atomic post-commit
    prune — run only AFTER a successful commit, so a failed save can never
    destroy the only good checkpoint."""
    pc = accelerator.project_configuration
    if pc.total_limit is None:
        return
    existing = _list_checkpoint_dirs(base)
    excess = len(existing) + room_for - pc.total_limit
    if excess <= 0:
        return
    import shutil

    for f in existing[:excess]:
        shutil.rmtree(os.path.join(base, f), ignore_errors=True)


def _record_checkpoint_event(accelerator, event: str, t0: float, path: str, **fields) -> None:
    """Telemetry sidecar: save/restore durations show up in the per-rank
    JSONL so checkpoint stalls are attributable from the same stream as the
    step times (telemetry.py)."""
    tel = getattr(accelerator, "telemetry", None)
    if tel is not None:
        tel.record_event(event, seconds=time.perf_counter() - t0, dir=path, **fields)


def _save_host_side_state(accelerator, state, output_dir: str) -> None:
    """Scheduler / dataloader / custom-object / step / scaler / per-rank RNG —
    the non-tensor sidecar files shared by both checkpoint formats."""
    if accelerator.is_main_process:
        if state.loss_scale is not None:
            with open(os.path.join(output_dir, f"{SCALER_NAME}.bin"), "wb") as f:
                pickle.dump(
                    {
                        "scale": float(np.asarray(state.loss_scale.scale)),
                        "growth_tracker": int(np.asarray(state.loss_scale.growth_tracker)),
                    },
                    f,
                )
        for i, scheduler in enumerate(accelerator._schedulers):
            with open(os.path.join(output_dir, f"{SCHEDULER_NAME}{'' if i == 0 else f'_{i}'}.bin"), "wb") as f:
                pickle.dump(scheduler.state_dict(), f)
        for i, dl in enumerate(accelerator._dataloaders):
            # Full loader state: sampler seed/epoch AND batches consumed this
            # epoch, so load_state resumes mid-epoch at the exact batch
            # (reference: dl_state_dict.bin via StatefulDataLoader,
            # checkpointing.py:107-153).
            if hasattr(dl, "state_dict"):
                payload = dl.state_dict()
            else:
                sampler = getattr(getattr(dl, "batch_sampler", None), "batch_sampler", None)
                sampler = getattr(sampler, "sampler", None) or getattr(
                    getattr(dl, "batch_sampler", None), "sampler", None
                )
                if sampler is None or not hasattr(sampler, "state_dict"):
                    continue
                payload = sampler.state_dict()
            with open(os.path.join(output_dir, f"{SAMPLER_NAME}{'' if i == 0 else f'_{i}'}.bin"), "wb") as f:
                pickle.dump(payload, f)
        for i, obj in enumerate(accelerator._custom_objects):
            with open(os.path.join(output_dir, f"custom_checkpoint_{i}.pkl"), "wb") as f:
                pickle.dump(obj.state_dict(), f)
        with open(os.path.join(output_dir, "accelerator_step.bin"), "wb") as f:
            pickle.dump({"step": accelerator.step}, f)

    # Per-rank RNG state (reference: checkpointing.py:154-179).
    with open(
        os.path.join(output_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl"), "wb"
    ) as f:
        pickle.dump(rng_state(), f)


from .utils.constants import ORBAX_DIR_NAME as _ORBAX_DIR  # shared with utils/fsdp_utils.py


def _orbax_payload(state) -> dict:
    payload = {"params": state.params, "opt_state": state.opt_state, "step": state.step}
    if state.extra_state:
        payload["extra_state"] = state.extra_state
    return payload


def _save_distributed_state(accelerator, state, output_dir: str, block: bool = True) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(output_dir, _ORBAX_DIR))
    if block:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, _orbax_payload(state), force=True)
        return
    # Async: orbax's save blocks only until device->host copies finish, then
    # persists to storage in a background thread — training resumes while
    # bytes stream out (safe with donated step buffers: the snapshot is
    # already on host). The checkpointer must outlive the call; it lives on
    # the accelerator and wait_for_checkpoint()/end_training drain it.
    ckptr = getattr(accelerator, "_async_checkpointer", None)
    if ckptr is None:
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        accelerator._async_checkpointer = ckptr
    else:
        ckptr.wait_until_finished()  # one in-flight save at a time
    ckptr.save(path, args=ocp.args.StandardSave(_orbax_payload(state)), force=True)


def _load_distributed_state(accelerator, state, input_dir: str):
    """Restore straight to the live mesh's shardings — each process reads only
    the byte ranges its shards need (TensorStore), no host gather inverse."""
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    shardings = accelerator._state_shardings

    def _abstract(x, s):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
        return x

    from jax.sharding import NamedSharding, PartitionSpec

    replicated = (
        NamedSharding(accelerator.mesh, PartitionSpec())
        if getattr(accelerator, "mesh", None) is not None else None
    )
    target = {
        "params": jax.tree.map(_abstract, state.params, shardings.params),
        "opt_state": jax.tree.map(_abstract, state.opt_state, shardings.opt_state),
        "step": jax.ShapeDtypeStruct(state.step.shape, state.step.dtype, sharding=replicated),
    }
    if state.extra_state:
        extra_sh = getattr(shardings, "extra_state", None)
        target["extra_state"] = (
            jax.tree.map(_abstract, state.extra_state, extra_sh)
            if extra_sh is not None
            else jax.tree.map(lambda x: _abstract(x, None), state.extra_state)
        )
    path = os.path.abspath(os.path.join(input_dir, _ORBAX_DIR))
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, target)
    return state.replace(
        step=_restore_scalar_like(restored["step"], state.step, jnp.int32),
        params=restored["params"],
        opt_state=restored["opt_state"],
        extra_state=restored.get("extra_state", state.extra_state),
    )


def _write_plan_sidecar(accelerator, write_dir: str) -> None:
    """Topology sidecar (plan_manifest.json) for elastic restore. Managed
    saves only — when neither fault tolerance nor elastic resharding is
    active the unmanaged checkpoint byte layout stays untouched. Written
    into the staging dir, so an atomic commit hashes and certifies it like
    every other checkpoint file."""
    ft = getattr(accelerator, "fault_tolerance", None)
    elastic = getattr(accelerator, "elastic", None)
    if ft is None and elastic is None:
        return
    try:
        from .resharding import write_plan_manifest

        write_plan_manifest(accelerator, write_dir)
    except Exception:
        logger.warning(
            "failed to write plan manifest (checkpoint remains loadable on "
            "the same topology)", exc_info=True,
        )


def _live_topology(accelerator) -> tuple[int, Optional[dict]]:
    """(device count, layout dict) of the running mesh, for topology checks."""
    n_devices = len(accelerator.state.devices)
    pc = accelerator.state.parallelism_config
    return n_devices, (pc.layout_dict() if pc is not None else None)


def _reshard_executor_for_load(accelerator, input_dir: str):
    """Topology governance at the top of a restore: compare the checkpoint's
    plan manifest against the live mesh BEFORE any deserialization. Returns a
    ``ReshardExecutor`` when the topologies differ and elastic restore is on;
    ``None`` when they match (or the checkpoint predates plan manifests);
    raises :class:`TopologyMismatchError` when they differ and elastic
    restore is off (or ``resize_policy="fail"``)."""
    from .resharding import (
        raise_topology_mismatch,
        read_plan_manifest,
        topology_matches,
    )

    manifest = read_plan_manifest(input_dir)
    if manifest is None:
        return None
    n_devices, layout = _live_topology(accelerator)
    if topology_matches(manifest, n_devices, layout):
        return None
    elastic = getattr(accelerator, "elastic", None)
    if elastic is None or not elastic.elastic_restore or elastic.resize_policy == "fail":
        raise_topology_mismatch(manifest, n_devices, layout, input_dir)
    from .resharding import describe_topology

    logger.info(
        "elastic restore: checkpoint topology %s -> live %s; planning "
        "redistribution (staging budget %d MiB)",
        describe_topology(
            int(manifest.get("n_devices", manifest.get("world_size", 0))),
            manifest.get("layout"),
        ),
        describe_topology(n_devices, layout),
        elastic.staging_budget_bytes // (1024 * 1024),
        main_process_only=True,
    )
    return elastic.executor(accelerator.state.mesh, manifest)


def _finalize_save(accelerator, write_dir: str, final_dir: str, step_host) -> None:
    """Commit point of an atomic save + post-commit housekeeping. No-op
    (besides the iteration bump the callers keep) for legacy saves."""
    pc = accelerator.project_configuration
    ft = getattr(accelerator, "fault_tolerance", None)
    atomic = ft is not None and ft.atomic
    # All ranks finished writing into the staging dir before the main
    # process hashes and renames it — the manifest must certify every rank's
    # files (per-rank RNG pickles included).
    accelerator.wait_for_everyone()
    if atomic and accelerator.is_main_process:
        ft.commit(write_dir, final_dir, step_host)
    if pc.automatic_checkpoint_naming:
        pc.iteration += 1
    accelerator.wait_for_everyone()
    # total_limit pruning moves AFTER the successful commit under atomic
    # saves: a save that dies mid-write leaves every older checkpoint
    # untouched (the legacy path keeps its pre-save prune for byte-identical
    # default-off behavior).
    if atomic and pc.automatic_checkpoint_naming and accelerator.is_main_process:
        _prune_total_limit(accelerator, os.path.dirname(final_dir), room_for=0)


def save_accelerator_state(
    accelerator,
    output_dir: Optional[str] = None,
    safe_serialization: bool = True,
    block: bool = True,
) -> str:
    t_save0 = time.perf_counter()
    pc = accelerator.project_configuration
    ft = getattr(accelerator, "fault_tolerance", None)
    atomic = ft is not None and ft.atomic
    # Any save first drains an in-flight async save: pruning below may rmtree
    # the directory it is persisting into, and a sync save with force=True
    # would race the background writer on the same path.
    if hasattr(accelerator, "wait_for_checkpoint"):
        accelerator.wait_for_checkpoint()
    output_dir = _checkpoint_dir(accelerator, output_dir)
    if pc.automatic_checkpoint_naming and accelerator.is_main_process:
        base = os.path.dirname(output_dir)
        os.makedirs(base, exist_ok=True)
        # total_limit pruning (reference: accelerator.py:3622-3647). Under
        # atomic saves this moves to _finalize_save (post-commit) so a
        # failed save can no longer destroy the only good checkpoint.
        if not atomic:
            _prune_total_limit(accelerator, base, room_for=1)
    accelerator.wait_for_everyone()
    if atomic:
        from .fault_tolerance import staging_path

        write_dir = staging_path(output_dir)
        if (
            accelerator.is_main_process
            and os.path.isdir(write_dir)
            and not ft.consume_prearmed(write_dir)
        ):
            # Stale staging from a previous failed/killed attempt: it is
            # unverifiable by construction — start clean. (A PRE-ARMED
            # staging dir — save_state just cleared it and ran the pre-save
            # hooks into it — is kept: those sidecar files ride this commit.)
            import shutil

            shutil.rmtree(write_dir)
        accelerator.wait_for_everyone()
    else:
        write_dir = output_dir
    os.makedirs(write_dir, exist_ok=True)

    state = accelerator._train_state
    if state is None:
        raise RuntimeError("Nothing prepared; call accelerator.prepare(...) first.")

    # Model params → name-keyed safetensors (fp32 masters, gathered to host).
    # fsdp_plugin.state_dict_type picks the file layout (reference:
    # FULL_STATE_DICT = one file, SHARDED_STATE_DICT = size-split shards +
    # index, utils/fsdp_utils.py:103-337); both are name-keyed and
    # reshard-safe, so either loads into any mesh.
    # DISTRIBUTED_STATE_DICT goes through orbax/TensorStore instead: every
    # process writes its own shards concurrently and NOTHING gathers to host
    # rank 0 — the pod-scale path (role of the reference's torch-DCP
    # sharded-state-dict dirs; restore reshards to whatever mesh is live).
    plugin = getattr(accelerator, "fsdp_plugin", None)
    if block is False and not (
        plugin is not None and plugin.state_dict_type == "DISTRIBUTED_STATE_DICT"
    ):
        logger.warning(
            "save_state(block=False) is only async for "
            "DISTRIBUTED_STATE_DICT (orbax) checkpoints; the safetensors "
            "gather path saves synchronously."
        )
    if plugin is not None and plugin.state_dict_type == "DISTRIBUTED_STATE_DICT":
        if len(accelerator._train_states) > 1:
            raise NotImplementedError(
                "DISTRIBUTED_STATE_DICT checkpointing currently saves a single "
                "prepared model; use FULL/SHARDED_STATE_DICT for multi-model "
                "training runs."
            )
        if atomic and not block:
            # The manifest+rename commit certifies bytes already on disk; an
            # async background writer would commit a half-persisted dir.
            logger.warning_once(
                "fault_tolerance: atomic checkpoints commit only after every "
                "byte persists — save_state(block=False) runs blocking while "
                "a FaultToleranceKwargs handler is active."
            )
            block = True
        _save_distributed_state(accelerator, state, write_dir, block=block)
        _save_host_side_state(accelerator, state, write_dir)
        _write_plan_sidecar(accelerator, write_dir)
        _finalize_save(accelerator, write_dir, output_dir, int(np.asarray(state.step)))
        _record_checkpoint_event(
            accelerator, "checkpoint_save", t_save0, output_dir,
            format="orbax", blocking=bool(block),
        )
        logger.info(
            f"Saved distributed (orbax) state to {output_dir}"
            + ("" if block else " (async: persisting in background)"),
            main_process_only=True,
        )
        return output_dir
    max_shard = (
        "5GB" if plugin is None or plugin.state_dict_type == "SHARDED_STATE_DICT" else 10**15
    )
    params_host = to_global_host(state.params)
    if accelerator.is_main_process:
        save_sharded_safetensors(
            flatten_state_dict(params_host), write_dir,
            max_shard_size=max_shard, weights_name=f"{MODEL_NAME}.safetensors",
        )

    # Optimizer state: flattened name-keyed arrays + treedef-free aux.
    opt_host = jax.tree.map(
        lambda x: to_global_host(x) if hasattr(x, 'shape') else x, state.opt_state
    )
    step_host = int(np.asarray(state.step))
    # Non-param collections (flax batch_stats etc.) ride along so BatchNorm
    # models resume with their running statistics.
    extra_host = (
        jax.tree.map(to_global_host, state.extra_state)
        if state.extra_state else None
    )
    if accelerator.is_main_process:
        with open(os.path.join(write_dir, f"{OPTIMIZER_NAME}.bin"), "wb") as f:
            pickle.dump(
                {"opt_state": opt_host, "step": step_host, "extra_state": extra_host}, f
            )

    # Multi-model slots (GAN/distillation): reference filename convention —
    # model_1.safetensors / optimizer_1.bin per extra prepared model
    # (reference: checkpointing.py save_accelerator_state enumerates models).
    for i, extra_st in enumerate(accelerator._train_states[1:], start=1):
        # Gathers are collectives — EVERY process must enter them; only the
        # file writes are rank-0 (same split as the primary path above).
        params_host_i = to_global_host(extra_st.params)
        opt_host_i = jax.tree.map(
            lambda x: to_global_host(x) if hasattr(x, "shape") else x,
            extra_st.opt_state,
        )
        extra_host_i = (
            jax.tree.map(to_global_host, extra_st.extra_state)
            if extra_st.extra_state else None
        )
        if accelerator.is_main_process:
            save_sharded_safetensors(
                flatten_state_dict(params_host_i), write_dir,
                max_shard_size=max_shard, weights_name=f"{MODEL_NAME}_{i}.safetensors",
            )
            payload = {
                "opt_state": opt_host_i,
                "step": int(np.asarray(extra_st.step)),
                "extra_state": extra_host_i,
            }
            with open(os.path.join(write_dir, f"{OPTIMIZER_NAME}_{i}.bin"), "wb") as f:
                pickle.dump(payload, f)
    _save_host_side_state(accelerator, state, write_dir)
    _write_plan_sidecar(accelerator, write_dir)

    _finalize_save(accelerator, write_dir, output_dir, step_host)
    _record_checkpoint_event(
        accelerator, "checkpoint_save", t_save0, output_dir, format="safetensors",
    )
    logger.info(f"Saved accelerator state to {output_dir}", main_process_only=True)
    return output_dir


def _restore_scalar_like(value, live, dtype):
    """Device-put a restored scalar onto the LIVE array's sharding. A bare
    ``jnp.asarray`` lands uncommitted on device 0; that input signature
    differs from the jitted train step's committed, mesh-replicated output,
    so the first post-restore step would silently recompile — which the
    in-process rollback path (fault_tolerance.py sentinel="rollback") cannot
    afford: the chaos-train smoke pins 0 steady-state recompiles across a
    rollback."""
    import jax.numpy as jnp

    arr = jnp.asarray(np.asarray(value), dtype)
    sharding = getattr(live, "sharding", None)
    if sharding is not None:
        arr = jax.device_put(arr, sharding)
    return arr


def _restore_loss_scale(state, input_dir: str):
    loss_scale = state.loss_scale
    scaler_path = os.path.join(input_dir, f"{SCALER_NAME}.bin")
    if loss_scale is not None and os.path.exists(scaler_path):
        import jax.numpy as jnp

        with open(scaler_path, "rb") as f:
            sc = pickle.load(f)
        loss_scale = loss_scale.replace(
            scale=_restore_scalar_like(sc["scale"], loss_scale.scale, jnp.float32),
            growth_tracker=_restore_scalar_like(
                sc["growth_tracker"], loss_scale.growth_tracker, jnp.int32
            ),
        )
    return loss_scale


def load_accelerator_state(accelerator, input_dir: Optional[str] = None) -> str:
    t_load0 = time.perf_counter()
    if hasattr(accelerator, "wait_for_checkpoint"):
        accelerator.wait_for_checkpoint()  # never read a half-persisted save
    input_dir = _checkpoint_dir(accelerator, input_dir, for_load=True)
    ft = getattr(accelerator, "fault_tolerance", None)
    if ft is not None and ft.handler.verify_on_load:
        # Explicit paths get verified here; the automatic resolver's pick
        # was already verified during resolution and is skipped.
        ft.verify_before_load(input_dir)
    state = accelerator._train_state
    if state is None:
        raise RuntimeError("Call accelerator.prepare(...) before load_state().")

    # Topology governance: mismatch either raises (elastic off) or hands back
    # the executor that routes every leaf through the planned redistribution.
    resharder = _reshard_executor_for_load(accelerator, input_dir)
    elastic = getattr(accelerator, "elastic", None)

    if os.path.isdir(os.path.join(input_dir, _ORBAX_DIR)):
        new_state = _load_distributed_state(accelerator, state, input_dir)
        accelerator._train_state = new_state.replace(
            loss_scale=_restore_loss_scale(state, input_dir)
        )
        _load_host_side_state(accelerator, input_dir)
        if resharder is not None and elastic is not None:
            # TensorStore restores straight into the live shardings (each
            # process reads only its ranges), so the redistribution happened
            # inside the restore — record the planned schedule for telemetry.
            schedule = resharder.plan_tree(
                accelerator._train_state,
                accelerator._slot_meta[0]["state_shardings"],
                prefix="slot0",
            )
            stats = dict(schedule.summary(), wall_s=round(time.perf_counter() - t_load0, 6))
            elastic.note_reshard(stats, kind="restore-orbax")
        _record_checkpoint_event(
            accelerator, "checkpoint_load", t_load0, input_dir, format="orbax",
        )
        logger.info(
            f"Loaded distributed (orbax) state from {input_dir}", main_process_only=True
        )
        return input_dir

    flat = load_sharded_safetensors(input_dir, weights_name=f"{MODEL_NAME}.safetensors")
    loaded_tree = unflatten_state_dict(flat)

    # Re-map by name into the live (sharded) param structure.
    def _remap(current, new):
        if isinstance(current, dict):
            return {k: _remap(v, new[k]) for k, v in current.items()}
        return np.asarray(new).reshape(current.shape)

    params_host = _remap(jax.tree.map(lambda x: x, state.params), loaded_tree)
    shardings = accelerator._state_shardings
    if resharder is not None:
        new_params = resharder.put_tree(
            params_host, shardings.params, prefix="slot0/params"
        )
    else:
        new_params = jax.tree.map(
            lambda arr, s: jax.device_put(arr, s), params_host, shardings.params
        )

    opt_path = os.path.join(input_dir, f"{OPTIMIZER_NAME}.bin")
    if not os.path.exists(opt_path):
        raise FileNotFoundError(
            f"Checkpoint {input_dir} has no {OPTIMIZER_NAME}.bin — the save "
            "was interrupted or the directory is not a full training "
            "checkpoint. Pass FaultToleranceKwargs to "
            "Accelerator(kwargs_handlers=[...]): saves then commit "
            "atomically with a verification manifest and load_state() "
            "automatically skips torn checkpoints, restoring the newest "
            "verified one instead."
        )
    with open(opt_path, "rb") as f:
        opt_payload = pickle.load(f)
    if resharder is not None:
        new_opt = resharder.put_tree(
            opt_payload["opt_state"], shardings.opt_state, prefix="slot0/opt_state"
        )
    else:
        new_opt = jax.tree.map(
            lambda arr, s: jax.device_put(np.asarray(arr), s)
            if hasattr(arr, "shape") or np.isscalar(arr)
            else arr,
            opt_payload["opt_state"],
            shardings.opt_state,
        )
    loss_scale = _restore_loss_scale(state, input_dir)

    import jax.numpy as jnp

    extra_state = state.extra_state
    loaded_extra = opt_payload.get("extra_state")
    if loaded_extra is not None and extra_state is not None:
        extra_sh = getattr(shardings, "extra_state", None)
        if extra_sh is not None and resharder is not None:
            extra_state = resharder.put_tree(
                loaded_extra, extra_sh, prefix="slot0/extra_state"
            )
        elif extra_sh is not None:
            extra_state = jax.tree.map(
                lambda a, s: jax.device_put(np.asarray(a), s), loaded_extra, extra_sh
            )
        else:
            extra_state = jax.tree.map(lambda a: jnp.asarray(a), loaded_extra)

    accelerator._train_state = state.replace(
        step=_restore_scalar_like(opt_payload["step"], state.step, jnp.int32),
        params=new_params,
        opt_state=new_opt,
        loss_scale=loss_scale,
        extra_state=extra_state,
    )

    # Extra model slots (multi-model training): model_{i}.safetensors +
    # optimizer_{i}.bin, restored into each slot's own sharding plan.
    for i, extra_st in enumerate(accelerator._train_states[1:], start=1):
        weights_name = f"{MODEL_NAME}_{i}.safetensors"
        have_weights = os.path.exists(os.path.join(input_dir, weights_name)) or os.path.exists(
            os.path.join(input_dir, weights_name + ".index.json")
        )
        if not have_weights:
            if os.path.exists(os.path.join(input_dir, f"{OPTIMIZER_NAME}_{i}.bin")):
                raise FileNotFoundError(
                    f"Checkpoint has {OPTIMIZER_NAME}_{i}.bin but no {weights_name} "
                    f"— the save for model slot {i} was incomplete."
                )
            logger.warning(
                "Checkpoint %s has no %s; model slot %d keeps its live params "
                "(checkpoint predates this model, or a multi-model save was "
                "interrupted).",
                input_dir, weights_name, i,
            )
            continue
        slot_sh = accelerator._slot_meta[i]["state_shardings"]
        flat_i = load_sharded_safetensors(input_dir, weights_name=weights_name)
        params_i = _remap(jax.tree.map(lambda x: x, extra_st.params), unflatten_state_dict(flat_i))
        with open(os.path.join(input_dir, f"{OPTIMIZER_NAME}_{i}.bin"), "rb") as f:
            payload_i = pickle.load(f)
        if resharder is not None:
            new_params_i = resharder.put_tree(
                params_i, slot_sh.params, prefix=f"slot{i}/params"
            )
            new_opt_i = resharder.put_tree(
                payload_i["opt_state"], slot_sh.opt_state, prefix=f"slot{i}/opt_state"
            )
        else:
            new_params_i = jax.tree.map(
                lambda arr, s: jax.device_put(arr, s), params_i, slot_sh.params
            )
            new_opt_i = jax.tree.map(
                lambda arr, s: jax.device_put(np.asarray(arr), s)
                if hasattr(arr, "shape") or np.isscalar(arr)
                else arr,
                payload_i["opt_state"],
                slot_sh.opt_state,
            )
        extra_i = extra_st.extra_state
        if payload_i.get("extra_state") is not None and extra_i is not None:
            extra_sh_i = getattr(slot_sh, "extra_state", None)
            extra_i = (
                jax.tree.map(
                    lambda a, s: jax.device_put(np.asarray(a), s),
                    payload_i["extra_state"], extra_sh_i,
                )
                if extra_sh_i is not None
                else jax.tree.map(lambda a: jnp.asarray(a), payload_i["extra_state"])
            )
        accelerator._train_states[i] = extra_st.replace(
            step=_restore_scalar_like(payload_i["step"], extra_st.step, jnp.int32),
            params=new_params_i,
            opt_state=new_opt_i,
            extra_state=extra_i,
        )

    _load_host_side_state(accelerator, input_dir)

    if resharder is not None and elastic is not None:
        elastic.note_reshard(resharder.stats(), kind="restore")

    _record_checkpoint_event(
        accelerator, "checkpoint_load", t_load0, input_dir, format="safetensors",
    )
    logger.info(f"Loaded accelerator state from {input_dir}", main_process_only=True)
    return input_dir


def _load_host_side_state(accelerator, input_dir: str) -> None:
    for i, scheduler in enumerate(accelerator._schedulers):
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}{'' if i == 0 else f'_{i}'}.bin")
        if os.path.exists(path):
            with open(path, "rb") as f:
                scheduler.load_state_dict(pickle.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        path = os.path.join(input_dir, f"{SAMPLER_NAME}{'' if i == 0 else f'_{i}'}.bin")
        if os.path.exists(path):
            with open(path, "rb") as f:
                payload = pickle.load(f)
            if hasattr(dl, "load_state_dict") and "batches_yielded" in payload:
                # Arms mid-epoch fast-forward for the loader's next __iter__.
                dl.load_state_dict(payload)
            else:  # legacy checkpoint: bare sampler state
                sampler = getattr(getattr(dl, "batch_sampler", None), "batch_sampler", None)
                sampler = getattr(sampler, "sampler", None) or getattr(
                    getattr(dl, "batch_sampler", None), "sampler", None
                )
                if sampler is not None and hasattr(sampler, "load_state_dict"):
                    sampler.load_state_dict(payload)
    for i, obj in enumerate(accelerator._custom_objects):
        path = os.path.join(input_dir, f"custom_checkpoint_{i}.pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                obj.load_state_dict(pickle.load(f))
    step_path = os.path.join(input_dir, "accelerator_step.bin")
    if os.path.exists(step_path):
        with open(step_path, "rb") as f:
            accelerator.step = pickle.load(f)["step"]

    rng_path = os.path.join(input_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl")
    if os.path.exists(rng_path):
        with open(rng_path, "rb") as f:
            load_rng_state(pickle.load(f))


def save_custom_state(obj, path: str, index: int = 0):
    """(reference: checkpointing.py:323-332)"""
    with open(os.path.join(path, f"custom_checkpoint_{index}.pkl"), "wb") as f:
        pickle.dump(obj.state_dict(), f)


def load_custom_state(obj, path: str, index: int = 0):
    """(reference: checkpointing.py:334-341)"""
    with open(os.path.join(path, f"custom_checkpoint_{index}.pkl"), "rb") as f:
        obj.load_state_dict(pickle.load(f))
