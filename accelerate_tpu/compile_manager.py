"""Compile manager (layer L4 — compilation control).

PR 1's telemetry *detects* recompile storms (the watchdog samples the jitted
step's executable cache and fingerprints the offending batch); nothing in the
repo *prevented* them. On TPU every distinct batch shape pays a full XLA
trace + lower + compile — tens of seconds each at real-model scale — so a
stream of ragged batches, a ragged final batch each epoch, or a cold restart
are the dominant silent perf killers. :class:`CompileManager` makes the
compile boundary a managed artifact, three ways:

1. **Shape bucketing** — a bucket policy (``pow2`` ladder, explicit
   ``fixed`` ladders, or ``auto`` from previously observed shapes) pads the
   batch and sequence dims at the device boundary
   (:meth:`CompileManager.bucket_pad`, called by
   ``BaseDataLoader._device_put_batch``), so a stream of ragged batches
   compiles at most ``len(buckets)`` executables instead of one per shape.

2. **AOT warmup** — every distinct post-bucketing ``(shape, dtype)``
   signature is recorded to a per-project ``shapes_manifest.jsonl`` (fed both
   by the manager's own step observation and by the telemetry watchdog's
   digests). On the next run, ``prepare_train_step`` warms every manifest
   entry **before step 0**. Two modes:

   - ``"execute"`` (default): run the real jitted step on a *copy* of the
     train state with zero-filled dummy batches. This is the only mode that
     populates jit's dispatch cache — measured on jax 0.4.x,
     ``lower().compile()`` leaves ``_cache_size()`` at 0, so an AOT-only
     warmup still pays trace+dispatch insertion (and the recompile-watchdog
     count) on the first real batch. Each signature is executed
     ``warmup_calls`` times (default 2) to also absorb the donated-buffer
     layout specialization TPU backends do on the second call.
   - ``"aot"``: classic ``jit(...).lower(abstract).compile()``. Cheaper (no
     state copy, no step executed) and it primes the *persistent* cache, but
     the first real call per shape still re-traces.

3. **Persistent-cache control** — the bare ``JitConfig.persistent_cache_dir``
   passthrough becomes a managed cache: the dir is validated/created at
   ``Accelerator`` init (``warning_once`` instead of handing a bad path to
   ``jax.config``), hit/miss and size stats surface in the telemetry
   summary, and ``close()`` prunes by mtime-LRU to a byte budget.

Enabled by passing :class:`~accelerate_tpu.utils.CompileKwargs` to
``Accelerator(kwargs_handlers=[...])``. Off by default: without the handler
``accelerator.compile_manager`` is ``None`` and every hook site is a single
``None`` check — behavior is byte-identical to the unmanaged path.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger

logger = get_logger(__name__)

MANIFEST_NAME = "shapes_manifest.jsonl"
CACHE_SUBDIR = "compile_cache"


# ---------------------------------------------------------------------------
# Bucket-policy math (pure functions — unit-tested directly)
# ---------------------------------------------------------------------------


def pow2_bucket(n: int, min_bucket: int = 1, max_bucket: Optional[int] = None) -> Optional[int]:
    """Smallest power of two >= ``n`` (floored at ``min_bucket``), or ``None``
    when it would exceed ``max_bucket`` — the oversize fall-through."""
    if n <= 0:
        return min_bucket
    b = max(min_bucket, 1 << (int(n) - 1).bit_length())
    if max_bucket is not None and b > max_bucket:
        return None
    return b


def ladder_bucket(n: int, ladder) -> Optional[int]:
    """Smallest ladder rung >= ``n``, or ``None`` when ``n`` overshoots the
    ladder."""
    for b in sorted(int(x) for x in ladder):
        if n <= b:
            return b
    return None


# ---------------------------------------------------------------------------
# Batch spec (de)serialization — what the manifest stores per signature
# ---------------------------------------------------------------------------


def tree_to_spec(tree) -> Any:
    """JSON-serializable skeleton of a batch pytree: containers survive as
    dict/list/tuple, array leaves become ``{"shape", "dtype"}``. Covers every
    batch structure the loaders emit (dicts, tuples, bare arrays)."""
    if isinstance(tree, dict):
        return {"kind": "dict", "items": {str(k): tree_to_spec(v) for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        return {
            "kind": "tuple" if isinstance(tree, tuple) else "list",
            "items": [tree_to_spec(v) for v in tree],
        }
    shape = getattr(tree, "shape", None)
    dtype = getattr(tree, "dtype", None)
    if shape is None or dtype is None:
        return {"kind": "opaque", "type": type(tree).__name__}
    return {"kind": "array", "shape": [int(d) for d in shape], "dtype": str(dtype)}


def spec_map_leaves(spec, fn):
    """Rebuild a pytree from a spec, calling ``fn(shape, dtype)`` per array
    leaf. Raises ``ValueError`` on opaque leaves (unwarmable signature)."""
    kind = spec.get("kind")
    if kind == "dict":
        return {k: spec_map_leaves(v, fn) for k, v in spec["items"].items()}
    if kind in ("list", "tuple"):
        items = [spec_map_leaves(v, fn) for v in spec["items"]]
        return tuple(items) if kind == "tuple" else items
    if kind == "array":
        return fn(tuple(spec["shape"]), spec["dtype"])
    raise ValueError(f"unwarmable manifest leaf of kind {kind!r}")


def spec_array_dims(spec, out: Optional[dict] = None) -> dict:
    """Collect observed dim sizes from a spec: ``{"batch": set, "seq": set}``
    — the raw material for the ``auto`` bucket ladder."""
    if out is None:
        out = {"batch": set(), "seq": set()}
    kind = spec.get("kind")
    if kind == "dict":
        for v in spec["items"].values():
            spec_array_dims(v, out)
    elif kind in ("list", "tuple"):
        for v in spec["items"]:
            spec_array_dims(v, out)
    elif kind == "array":
        shape = spec["shape"]
        if len(shape) >= 1:
            out["batch"].add(int(shape[0]))
        if len(shape) >= 2:
            out["seq"].add(int(shape[1]))
    return out


def batch_digest(batch) -> str:
    """Shape/dtype fingerprint — same digest the telemetry watchdog records,
    so manifest entries and watchdog warnings cross-reference."""
    from .telemetry import _batch_digest

    return _batch_digest(batch)


# ---------------------------------------------------------------------------
# Shapes manifest — the cross-run memory of observed signatures
# ---------------------------------------------------------------------------


class ShapesManifest:
    """Append-only JSONL of observed batch signatures, one line per NEW
    signature: ``{"digest", "spec", "time"}``. Crash-safe like the telemetry
    report (each line is durable on its newline); duplicate digests are
    dropped at record time, so replaying a manifest is idempotent."""

    def __init__(self, path: str):
        self.path = path
        self._digests: set = set()
        self._entries: list[dict] = []
        self._load()

    def _load(self):
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        entry = json.loads(line)
                    except ValueError:
                        continue  # torn tail line from a preempted run
                    digest = entry.get("digest")
                    if digest and digest not in self._digests and "spec" in entry:
                        self._digests.add(digest)
                        self._entries.append(entry)
        except OSError as e:
            logger.warning("compile_manager: could not read shapes manifest %s: %s", self.path, e)

    @property
    def entries(self) -> list[dict]:
        return list(self._entries)

    def __len__(self):
        return len(self._entries)

    def __contains__(self, digest: str) -> bool:
        return digest in self._digests

    def record(self, digest: str, spec) -> bool:
        """Append one signature; returns True when it was new."""
        if digest in self._digests:
            return False
        entry = {"digest": digest, "spec": spec, "time": time.time()}
        self._digests.add(digest)
        self._entries.append(entry)
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a", buffering=1) as fh:
                fh.write(json.dumps(entry) + "\n")
        except OSError as e:
            logger.warning_once(
                "compile_manager: cannot append to shapes manifest %s (%s) — "
                "warmup will not cover this run's shapes on restart.", self.path, str(e)
            )
        return True


def manifest_path_for(accelerator) -> Optional[str]:
    """Default manifest location: ``<project_dir>/compile_cache/shapes_manifest.jsonl``."""
    if accelerator.project_dir is None:
        return None
    return os.path.join(accelerator.project_dir, CACHE_SUBDIR, MANIFEST_NAME)


def record_watchdog_signature(accelerator, batch, digest: str) -> None:
    """Telemetry-watchdog → manifest bridge: called on every NEW step-batch
    digest the watchdog sees. Routes through the compile manager when one
    exists (shared dedup set); otherwise writes a standalone manifest under
    the project dir so a *future* run with the manager enabled can warm from
    a telemetry-only run's observations."""
    cm = getattr(accelerator, "compile_manager", None)
    if cm is not None:
        cm.record_digest(digest, batch)
        return
    manifest = getattr(accelerator, "_shapes_manifest", None)
    if manifest is None:
        path = manifest_path_for(accelerator)
        if path is None:
            return
        manifest = ShapesManifest(path)
        accelerator._shapes_manifest = manifest
    manifest.record(digest, tree_to_spec(batch))


# ---------------------------------------------------------------------------
# Persistent executable cache — validation, stats, LRU pruning
# ---------------------------------------------------------------------------


def configure_persistent_cache(jit_config) -> Optional[str]:
    """Validate ``JitConfig.persistent_cache_dir`` at Accelerator init:
    create it, check writability (``warning_once`` instead of silently
    handing a bad path to ``jax.config``), and wire the min-compile-time
    knob. Returns the validated path, or ``None`` when unusable."""
    path = jit_config.persistent_cache_dir
    if not path:
        return None
    path = os.path.abspath(os.path.expanduser(path))
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        logger.warning_once(
            "JitConfig.persistent_cache_dir=%s cannot be created (%s) — "
            "persistent compilation cache DISABLED for this run.", path, str(e)
        )
        return None
    if not os.access(path, os.W_OK):
        logger.warning_once(
            "JitConfig.persistent_cache_dir=%s is not writable — persistent "
            "compilation cache DISABLED for this run.", path
        )
        return None
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(jit_config.persistent_cache_min_compile_time_secs),
        )
    except (AttributeError, ValueError):  # older jax without the knob
        pass
    return path


class ManagedPersistentCache:
    """Size/hit accounting and LRU pruning over the XLA persistent
    compilation cache directory. JAX gives no hit/miss API, so misses are
    measured as files that appeared since this run started; hits are compile
    events the run observed beyond those (an estimate, labeled as such)."""

    def __init__(self, cache_dir: str, budget_bytes: Optional[int] = None):
        self.dir = cache_dir
        self.budget_bytes = budget_bytes
        self._baseline = set(self._files())

    def _files(self) -> dict:
        out = {}
        try:
            for root, _dirs, files in os.walk(self.dir):
                for name in files:
                    p = os.path.join(root, name)
                    try:
                        st = os.stat(p)
                    except OSError:
                        continue
                    out[p] = (st.st_size, st.st_mtime)
        except OSError:
            pass
        return out

    def stats(self, compile_events: int = 0) -> dict:
        files = self._files()
        new = [p for p in files if p not in self._baseline]
        misses = len(new)
        return {
            "dir": self.dir,
            "files": len(files),
            "bytes": int(sum(s for s, _ in files.values())),
            "misses": misses,  # executables compiled fresh this run
            "estimated_hits": max(0, int(compile_events) - misses),
        }

    def prune(self) -> dict:
        """Remove oldest-mtime entries until the cache fits the byte budget.
        Never removes files created by THIS run (they are the hot set)."""
        if not self.budget_bytes:
            return {"removed_files": 0, "removed_bytes": 0}
        files = self._files()
        total = sum(s for s, _ in files.values())
        removed_files = removed_bytes = 0
        if total <= self.budget_bytes:
            return {"removed_files": 0, "removed_bytes": 0}
        # Oldest first; this run's entries are excluded from eviction.
        evictable = sorted(
            ((p, sz, mt) for p, (sz, mt) in files.items() if p in self._baseline),
            key=lambda x: x[2],
        )
        for p, sz, _mt in evictable:
            if total <= self.budget_bytes:
                break
            try:
                os.remove(p)
            except OSError:
                continue
            total -= sz
            removed_files += 1
            removed_bytes += sz
        if removed_files:
            logger.info(
                "compile_manager: pruned %d cache entries (%d bytes) from %s "
                "to meet the %d-byte budget.",
                removed_files, removed_bytes, self.dir, self.budget_bytes,
            )
        return {"removed_files": removed_files, "removed_bytes": removed_bytes}


# ---------------------------------------------------------------------------
# The manager
# ---------------------------------------------------------------------------


def _cache_size(fn) -> Optional[int]:
    size_fn = getattr(fn, "_cache_size", None)
    if callable(size_fn):
        try:
            return int(size_fn())
        except Exception:
            return None
    return None


class CompileManager:
    """Owned by :class:`~accelerate_tpu.Accelerator` when a
    :class:`~accelerate_tpu.utils.CompileKwargs` handler is passed. One
    instance per Accelerator; all hook sites are ``None`` checks when off."""

    def __init__(self, accelerator, handler):
        self.accelerator = accelerator
        self.handler = handler
        path = handler.manifest_path or manifest_path_for(accelerator)
        self.manifest = ShapesManifest(path) if path else None
        self._seen: set = set(self.manifest._digests) if self.manifest else set()
        self._steps: list[dict] = []
        self._auto_ladders: Optional[dict] = None
        self._plan = None  # resolved ParallelPlan, via note_plan
        self.pad_events = 0
        self.oversize_events = 0
        self.warmup_stats = {"signatures_compiled": 0, "seconds": 0.0, "skipped": 0}
        budget = handler.cache_budget_bytes
        if budget is None:
            budget = accelerator.jit_config.persistent_cache_budget_bytes
        cache_dir = accelerator.jit_config.persistent_cache_dir
        self.cache = ManagedPersistentCache(cache_dir, budget) if cache_dir else None

    # -- auto-parallelism plan hook ---------------------------------------

    def note_plan(self, plan) -> None:
        """Warm toward the chosen plan's step shape (planner.py): the plan's
        sequence length and per-rank batch are grafted onto the fixed/auto
        bucket ladders so the very first real batch pads to the planned
        shape — the step the warmup compiles is the step training runs."""
        self._plan = plan
        seq = int(getattr(plan, "seq", 0) or 0)
        layout = getattr(plan, "layout", None) or {}
        dp = max(1, int(layout.get("dp_replicate", 1)) * int(layout.get("dp_shard", 1)))
        batch = int(getattr(plan, "per_chip_batch", 0) or 0) * int(
            getattr(plan, "n_devices", 0) or 0
        ) // dp
        h = self.handler
        for kind, dim in (("seq", seq), ("batch", batch)):
            if dim <= 0:
                continue
            ladder = h.seq_buckets if kind == "seq" else h.batch_buckets
            if ladder is not None and dim not in ladder:
                ladder.append(dim)
                ladder.sort()
            if self._auto_ladders and dim not in self._auto_ladders.get(kind, []):
                self._auto_ladders[kind] = sorted(self._auto_ladders[kind] + [dim])

    # -- bucketing ---------------------------------------------------------

    def _ladder(self, kind: str):
        h = self.handler
        return h.batch_buckets if kind == "batch" else h.seq_buckets

    def _auto_ladder(self, kind: str):
        if self._auto_ladders is None or self._auto_ladders.get("_n") != len(self.manifest or ()):
            dims = {"batch": set(), "seq": set()}
            for entry in (self.manifest.entries if self.manifest else []):
                spec_array_dims(entry.get("spec", {}), dims)
            self._auto_ladders = {
                "batch": sorted(dims["batch"]),
                "seq": sorted(dims["seq"]),
                "_n": len(self.manifest or ()),
            }
        return self._auto_ladders[kind]

    def bucket_for(self, n: int, kind: str = "seq") -> int:
        """Bucketed size for a raw dim of ``n``. Oversize (past ``max_bucket``
        or off the ladder) falls through to the TRUE size with a one-time
        warning — shipping the real shape beats crashing, but each distinct
        oversize shape costs a compile."""
        h = self.handler
        policy = h.buckets
        if policy is None:
            return n
        n = int(n)
        if policy == "fixed":
            ladder = self._ladder(kind)
            if not ladder:
                logger.warning_once(
                    "CompileKwargs(buckets='fixed') without %s_buckets — dim "
                    "left unbucketed.", kind
                )
                return n
            b = ladder_bucket(n, ladder)
        elif policy == "auto":
            ladder = self._auto_ladder(kind)
            b = ladder_bucket(n, ladder) if ladder else None
            if b is None:  # unseen size: fall back to the pow2 ladder
                b = pow2_bucket(n, h.min_bucket, h.max_bucket)
        else:  # pow2
            b = pow2_bucket(n, h.min_bucket, h.max_bucket)
        if b is None:
            self.oversize_events += 1
            logger.warning_once(
                "compile_manager: %s dim %d exceeds the largest bucket — "
                "shipping the true shape (one compile per distinct oversize "
                "shape). Raise max_bucket or extend the ladder.", kind, n
            )
            return n
        return b

    def bucket_pad(self, batch, batch_size_hint: Optional[int] = None):
        """Pad a host-side numpy batch to bucket shapes at the device
        boundary. Axis 0 is the batch dim on every array leaf (repo-wide
        convention); axis 1 of rank>=2 leaves is the sequence dim.

        - batch dim: padded up to ``batch_size_hint`` (the loader's full
          batch size — so the ragged final batch of a ``drop_last=False``
          epoch stops costing a one-off recompile) or, without a hint, to the
          policy bucket. ``batch_pad_mode="repeat"`` cycles real samples
          (the same semantics ``even_batches`` already gives the final batch;
          duplicate tails are trimmed by ``gather_for_metrics`` via
          ``GradientState.remainder``) and ``"zero"`` zero-fills.
        - sequence dim: zero-padded (``seq_pad_value``) up to its bucket.
          Only leaves whose axis-1 size equals the batch's REFERENCE
          sequence length (axis 1 of the first rank>=2 leaf — the same
          convention telemetry's token counter uses) participate: that keeps
          aligned leaves (ids/labels/positions) padded in lockstep while a
          ``(B, num_classes)`` or ``(B, 1)`` leaf riding in the same dict is
          left untouched.
        - ``emit_mask=True`` on dict batches ALWAYS adds a ``mask_key`` leaf
          (1.0 = real element) so the batch structure — and therefore the
          compiled signature — stays fixed whether or not padding occurred.
        """
        h = self.handler
        leaves = jax.tree_util.tree_leaves(batch)
        arrs = [l for l in leaves if getattr(l, "ndim", 0) >= 1]
        if not arrs:
            return batch
        raw_b = int(arrs[0].shape[0])
        if h.bucket_batch:
            if batch_size_hint is not None and raw_b <= int(batch_size_hint):
                target_b = int(batch_size_hint)
            else:
                target_b = self.bucket_for(raw_b, "batch")
        else:
            target_b = raw_b
        changed = target_b != raw_b
        first2 = next((a for a in arrs if a.ndim >= 2), None)
        ref_s = int(first2.shape[1]) if first2 is not None else None
        target_s = self.bucket_for(ref_s, "seq") if (h.bucket_seq and ref_s) else ref_s

        def _pad(arr):
            nonlocal changed
            if getattr(arr, "ndim", 0) < 1:
                return arr
            out = np.asarray(arr)
            if target_b > out.shape[0]:
                if h.batch_pad_mode == "repeat":
                    idx = np.arange(target_b) % out.shape[0]
                    out = np.take(out, idx, axis=0)
                else:
                    width = [(0, target_b - out.shape[0])] + [(0, 0)] * (out.ndim - 1)
                    out = np.pad(out, width, constant_values=0)
            if out.ndim >= 2 and out.shape[1] == ref_s and target_s > ref_s:
                width = [(0, 0), (0, target_s - ref_s)] + [(0, 0)] * (out.ndim - 2)
                out = np.pad(out, width, constant_values=h.seq_pad_value)
                changed = True
            return out

        padded = jax.tree.map(_pad, batch)
        if changed:
            self.pad_events += 1
        if h.emit_mask and isinstance(padded, dict):
            if ref_s is not None:
                mask = np.zeros((target_b, target_s), np.float32)
                mask[:raw_b, :ref_s] = 1.0
            else:
                mask = np.zeros((target_b,), np.float32)
                mask[:raw_b] = 1.0
            padded[h.mask_key] = mask
        return padded

    # -- signature observation (hot path when enabled) ---------------------

    def observe(self, batch) -> None:
        """Record the (post-bucketing, global) batch signature; one manifest
        line per new digest. Called by the prepared step wrapper."""
        digest = batch_digest(batch)
        if digest in self._seen:
            return
        self._seen.add(digest)
        if self.manifest is not None:
            self.manifest.record(digest, tree_to_spec(batch))

    def record_digest(self, digest: str, batch) -> None:
        """Watchdog bridge entry point (digest already computed)."""
        if digest in self._seen:
            return
        self._seen.add(digest)
        if self.manifest is not None:
            self.manifest.record(digest, tree_to_spec(batch))

    # -- generation signatures (decode loops) ------------------------------

    def record_generation_signature(self, plan: str, batch: int, prompt_len: int,
                                    max_new_tokens: int, settings: Optional[dict] = None) -> None:
        """Record one ``generate()`` call signature (post-bucketing prompt
        shape + sampling settings) so :meth:`warmup_generation` can compile
        decode loops before the first request on a restart."""
        settings = dict(settings or {})
        digest = "gen:{}:{}x{}+{}:{}".format(
            plan, int(batch), int(prompt_len), int(max_new_tokens),
            "|".join(f"{k}={settings[k]}" for k in sorted(settings)),
        )
        if digest in self._seen:
            return
        self._seen.add(digest)
        if self.manifest is not None:
            spec = {
                "kind": "generation", "plan": plan, "batch": int(batch),
                "prompt_len": int(prompt_len),
                "max_new_tokens": int(max_new_tokens), "settings": settings,
            }
            self.manifest.record(digest, spec)

    def warmup_generation(self, model, generate_fn=None) -> int:
        """Compile every recorded generation signature for ``model``'s plan
        NOW (zero-filled dummy prompts through ``generate``) — the decode
        analog of the train-step warmup. Returns the number of signatures
        compiled; bad entries are skipped with a warning."""
        if self.manifest is None:
            return 0
        if generate_fn is None:
            from .generation import generate as generate_fn
        plan = type(model.module).__name__
        compiled = 0
        t0 = time.perf_counter()
        for entry in self.manifest.entries:
            spec = entry.get("spec") or {}
            if spec.get("kind") != "generation" or spec.get("plan") != plan:
                continue
            settings = spec.get("settings") or {}
            try:
                ids = np.zeros((spec["batch"], spec["prompt_len"]), np.int32)
                kwargs = {
                    k: settings.get(k)
                    for k in ("temperature", "top_k", "top_p", "eos_token_id",
                              "pad_token_id")
                    if settings.get(k) is not None
                }
                if settings.get("masked"):
                    kwargs["attention_mask"] = np.ones_like(ids)
                generate_fn(model, ids, max_new_tokens=spec["max_new_tokens"],
                            **kwargs)
                compiled += 1
            except Exception as e:  # warmup must never kill serving/inference
                logger.warning(
                    "compile_manager: generation warmup failed for %s: %s: %s",
                    entry.get("digest", "?")[:80], type(e).__name__, e,
                )
        if compiled:
            seconds = time.perf_counter() - t0
            self.warmup_stats["signatures_compiled"] += compiled
            self.warmup_stats["seconds"] += seconds
            logger.info(
                "compile_manager: warmed %d generation signature(s) in %.2fs "
                "— the first request will not pay these compiles.",
                compiled, seconds,
            )
        return compiled

    def prefill_ladder(self, max_len: int, min_chunk: int = 16,
                       max_chunk: int = 256) -> list:
        """Chunk-size ladder for the serving engine's chunked prefill: the
        handler's explicit seq buckets when the policy is ``fixed``, else
        the pow2 ladder clipped to ``[min_chunk, min(max_chunk, max_len)]``
        — so prefill executables and bucketed batch shapes share rungs."""
        from .serving import default_prefill_ladder

        h = self.handler
        if h.buckets == "fixed" and h.seq_buckets:
            rungs = sorted({int(x) for x in h.seq_buckets if int(x) <= max_len})
            if rungs:
                return rungs
        lo = max(min_chunk, h.min_bucket)
        hi = min(max_chunk, h.max_bucket) if h.max_bucket else max_chunk
        return default_prefill_ladder(max_len, lo, max(lo, hi))

    # -- step registration + warmup ----------------------------------------

    def register_step(self, jitted, slot: int = 0, label: str = "train_step",
                      warmable: bool = True) -> None:
        """Called by ``prepare_train_step`` with the underlying jitted step.
        When warmup is on, every known manifest signature is compiled NOW —
        before step 0 — so restarts skip first-step compile stalls."""
        entry = {"fn": jitted, "slot": slot, "label": label,
                 "warmable": warmable, "warmed": set()}
        self._steps.append(entry)
        if self.handler.warmup != "off":
            self._warmup_entry(entry)

    def warmup(self) -> dict:
        """(Re-)warm every registered step against the current manifest.
        Idempotent: signatures already warmed for a step are skipped, so a
        second call compiles nothing."""
        for entry in self._steps:
            self._warmup_entry(entry)
        return dict(self.warmup_stats)

    def invalidate_steps(self) -> int:
        """Forget every warmed signature (elastic plan migration: the old
        executables were specialized to the previous mesh/shardings). The
        steps stay registered — jit retraces them for the new layout on the
        next call, and ``warmup()`` re-warms every manifest signature.
        Returns the number of executables dropped from the jit caches."""
        dropped = 0
        for entry in self._steps:
            fn = entry["fn"]
            try:
                dropped += int(fn._cache_size())
                fn.clear_cache()
            except Exception:
                pass
            entry["warmed"] = set()
        return dropped

    def _batch_sharding(self, ndim: int):
        from .parallel.sharding import batch_partition_spec

        acc = self.accelerator
        spec = batch_partition_spec(ndim, acc.state.parallelism_config)
        return jax.sharding.NamedSharding(acc.mesh, spec)

    def _build_batch(self, spec, abstract: bool):
        """Manifest spec → device batch: zero-filled global arrays for
        ``execute`` warmup, sharded ``ShapeDtypeStruct``s for ``aot``. The
        sharding MUST match what the loader ships (same NamedSharding) or the
        warmed executable would miss on the first real batch."""
        acc = self.accelerator

        def _leaf(shape, dtype):
            sharding = self._batch_sharding(len(shape))
            if abstract:
                return jax.ShapeDtypeStruct(shape, np.dtype(dtype), sharding=sharding)
            arr = np.zeros(shape, np.dtype(dtype))
            if acc.num_processes > 1:
                per = shape[0] // acc.num_processes
                if per * acc.num_processes != shape[0]:
                    raise ValueError(f"batch dim {shape[0]} not divisible by world")
                local = arr[: per] if per else arr
                return jax.make_array_from_process_local_data(sharding, local)
            return jax.device_put(arr, sharding)

        return spec_map_leaves(spec, _leaf)

    def _warmup_entry(self, entry: dict) -> None:
        if not entry["warmable"] or self.manifest is None or not len(self.manifest):
            return
        acc = self.accelerator
        states = getattr(acc, "_train_states", None)
        if not states or entry["slot"] >= len(states):
            return
        state = states[entry["slot"]]
        mode = self.handler.warmup
        pending = [
            e for e in self.manifest.entries
            if e["digest"] not in entry["warmed"]
            # Generation signatures belong to warmup_generation (they need a
            # model, not a train state).
            and (e.get("spec") or {}).get("kind") != "generation"
        ]
        if not pending:
            return
        t0 = time.perf_counter()
        compiled = 0
        work = None  # execute mode: one donated-safe copy, threaded across signatures
        for mentry in pending:
            try:
                batch = self._build_batch(mentry["spec"], abstract=(mode == "aot"))
            except (ValueError, TypeError) as e:
                entry["warmed"].add(mentry["digest"])  # never retry a bad spec
                self.warmup_stats["skipped"] += 1
                logger.warning_once(
                    "compile_manager: manifest signature %s is not warmable "
                    "(%s) — skipped.", mentry["digest"][:80], str(e)
                )
                continue
            try:
                if mode == "aot":
                    state_abs = jax.tree.map(
                        lambda x: jax.ShapeDtypeStruct(
                            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
                        )
                        if hasattr(x, "shape")
                        else x,
                        state,
                    )
                    entry["fn"].lower(state_abs, batch).compile()
                else:
                    if work is None:
                        # jnp.copy, not device_put-to-same-sharding: the
                        # latter aliases, and donation would then invalidate
                        # the REAL train state's buffers.
                        work = jax.tree.map(
                            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                            state,
                        )
                    for _ in range(max(1, self.handler.warmup_calls)):
                        work, _metrics = entry["fn"](work, batch)
            except Exception as e:  # warmup must never kill training
                logger.warning(
                    "compile_manager: warmup failed for signature %s: %s: %s",
                    mentry["digest"][:80], type(e).__name__, e,
                )
                continue
            entry["warmed"].add(mentry["digest"])
            compiled += 1
        if work is not None:
            try:
                jax.block_until_ready(work)  # honest warmup timing
            except Exception:
                pass
        seconds = time.perf_counter() - t0
        self.warmup_stats["signatures_compiled"] += compiled
        self.warmup_stats["seconds"] += seconds
        if compiled:
            logger.info(
                "compile_manager: warmed %d signature(s) for %s in %.2fs "
                "(mode=%s) — step 0 will not pay these compiles.",
                compiled, entry["label"], seconds, mode,
            )

    # -- reporting ---------------------------------------------------------

    def executable_count(self) -> int:
        """Total executables across registered step fns (jit dispatch-cache
        sizes) — the number the acceptance bar caps at ``len(buckets)``."""
        total = 0
        for entry in self._steps:
            size = _cache_size(entry["fn"])
            if size:
                total += size
        return total

    def cache_stats(self) -> Optional[dict]:
        if self.cache is None:
            return None
        return self.cache.stats(compile_events=self.executable_count())

    def summary(self) -> dict:
        out = {
            "bucket_policy": self.handler.buckets,
            "executables": self.executable_count(),
            "manifest_signatures": len(self.manifest) if self.manifest else 0,
            "pad_events": self.pad_events,
            "oversize_events": self.oversize_events,
            "warmup": dict(self.warmup_stats),
        }
        cache = self.cache_stats()
        if cache is not None:
            out["persistent_cache"] = cache
        return out

    def close(self) -> None:
        if self.cache is not None:
            self.cache.prune()
