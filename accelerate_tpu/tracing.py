"""Request-scoped distributed tracing with critical-path SLO attribution.

The serving stack is six composed subsystems (serving, disagg, chaos,
publish, autoscale, fault tolerance), each emitting aggregate telemetry —
but aggregates cannot answer "why did request 17 miss its deadline?".
``TraceRecorder`` records *spans keyed by request id* across the whole
lifecycle (queued, per-chunk prefill with lane id, KV handoff + every
retry/backoff, per-tick decode occupancy tagged with ``weights_version``,
quarantine, canary cohort membership) plus engine-level spans for resize
phases, publish phases, checkpoint save/restore, and chaos injections
annotated onto the span they hit.

Three consumers sit on top:

- ``explain(request_id)`` — critical-path SLO attribution: decomposes a
  request's measured TTFT into queue wait, prefill compute, handoff,
  retry backoff, and scheduler/drain stalls. The terms telescope: they
  sum to the measured TTFT within float tolerance *by construction*
  (the stall term is the remainder of disjoint measured sub-intervals),
  and the dominant term is named so "p95 TTFT breached" comes with
  evidence.
- ``export_chrome_trace(path)`` — Perfetto-loadable Chrome trace JSON
  with pid=subsystem, tid=lane/slot, and flow events stitching each KV
  handoff from its prefill lane to the decode slot it lands in.
- ``metrics_text()`` — Prometheus text-exposition snapshot of the live
  gauges (``stats()``/``window_stats()`` parity) for external scrapers.

Two clocks
----------
Every span carries a **tick-domain** clock (the engine's deterministic
tick counter) and optional **wall-clock** timestamps (``time.perf_counter``).
The tick-domain projection (``tick_trace()``) contains only
deterministic fields, so a seeded chaos run replays a *bit-identical*
tick-domain trace — the same invariant ``chaos.py`` guarantees for its
fault log. Wall clocks feed only ``explain()`` and the Chrome export.

Like every subsystem here the recorder is off by default and hooks are
zero-cost ``if tracing is not None`` checks; all tracing is host-side
Python — no extra device fetches, so the ONE-decode-executable /
0-steady-recompile invariants are untouched.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["TraceConfig", "TraceRecorder", "Span"]

# Subsystem -> Chrome trace pid. Stable small integers so two runs of the
# same workload produce identical metadata, and so Perfetto groups tracks
# the same way every time.
_PIDS = {
    "serving": 1,
    "prefill": 2,
    "handoff": 3,
    "decode": 4,
    "resize": 5,
    "publish": 6,
    "autoscale": 7,
    "checkpoint": 8,
    "chaos": 9,
    "watchdog": 10,
}


def _lane_id(lane: Any) -> Any:
    """Normalize a lane argument to its integer id: callers may pass the
    engine's internal lane object (disagg ``_Lane``) — spans must only carry
    JSON-serializable attrs."""
    if lane is None or isinstance(lane, (int, str)):
        return lane
    idx = getattr(lane, "index", None)
    return idx if idx is not None else str(lane)


@dataclass
class TraceConfig:
    """Config for :class:`TraceRecorder`.

    Attributes:
        enabled: master switch; a falsy config means no recorder is built.
        max_spans: hard cap on retained spans. Past it new spans are
            counted in ``dropped_spans`` (deterministically — the cap is
            hit at the same span index on a seeded replay) and a single
            warning is logged.
        wall_clock: record ``time.perf_counter()`` walls alongside the
            tick clock. Disable for strictly tick-domain traces;
            ``explain()`` then has no wall terms to attribute.
        max_requests: cap on per-request accounting entries retained for
            ``explain()``; oldest finished requests are evicted first.
    """

    enabled: bool = True
    max_spans: int = 200_000
    wall_clock: bool = True
    max_requests: int = 10_000

    @classmethod
    def from_value(cls, value: Any) -> Optional["TraceConfig"]:
        """Coerce a ``TelemetryKwargs.tracing`` value into a config.

        Accepts ``True`` (defaults), a dict of field overrides, an
        existing ``TraceConfig``, or falsy (disabled -> ``None``).
        """
        if not value:
            return None
        if isinstance(value, cls):
            return value if value.enabled else None
        if isinstance(value, dict):
            cfg = cls(**value)
            return cfg if cfg.enabled else None
        if value is True:
            return cls()
        raise TypeError(
            f"tracing must be bool, dict, or TraceConfig, got {type(value).__name__}"
        )


class Span:
    """One span. ``seq`` is a monotone id assigned at creation, which makes

    span ordering deterministic in the tick domain (creation order follows
    engine execution order, which is deterministic for tick-driven
    workloads). Wall fields (``t0``/``t1``) live outside the deterministic
    projection returned by ``tick_trace()``.
    """

    __slots__ = (
        "seq", "subsystem", "name", "kind", "tid", "request_id",
        "start_tick", "end_tick", "t0", "t1", "attrs", "flow",
    )

    def __init__(self, seq, subsystem, name, kind, tid, request_id,
                 start_tick, t0, attrs):
        self.seq = seq
        self.subsystem = subsystem
        self.name = name
        self.kind = kind
        self.tid = tid
        self.request_id = request_id
        self.start_tick = start_tick
        self.end_tick = start_tick
        self.t0 = t0
        self.t1 = t0
        self.attrs = attrs
        self.flow = None  # flow id for Chrome "s"/"f" stitching

    def tick_view(self) -> Dict[str, Any]:
        """Deterministic projection: no wall clocks, sorted attrs."""
        return {
            "seq": self.seq,
            "subsystem": self.subsystem,
            "name": self.name,
            "kind": self.kind,
            "tid": self.tid,
            "request_id": self.request_id,
            "start_tick": self.start_tick,
            "end_tick": self.end_tick,
            "attrs": dict(sorted(self.attrs.items())) if self.attrs else {},
        }


class _ReqTrace:
    """Per-request critical-path accumulator.

    Wall durations are accumulated *directly by the hooks* rather than
    re-derived from the span tree — backoff sleeps happen inside prefill
    dispatch walls, so deriving from spans would double count. The terms
    are disjoint measured sub-intervals of ``[submit_t, first_token_t]``;
    the stall term is the telescoping remainder, which makes the
    decomposition sum to the measured TTFT exactly.
    """

    __slots__ = (
        "id", "submit_t", "enqueue_t", "admit_t", "first_token_t", "done_t",
        "submit_tick", "done_tick", "status", "deadline_s",
        "queue_wait_s", "prefill_active_s", "handoff_s", "backoff_s",
        "decode_ticks", "retries", "prompt_tokens", "new_tokens",
        "weights_version", "canary", "lanes", "slot", "ttft_s",
        "drafted", "accepted",
    )

    def __init__(self, rid, tick, t, prompt_tokens, deadline_s):
        self.id = rid
        self.submit_t = t
        self.enqueue_t = t
        self.admit_t = None
        self.first_token_t = None
        self.done_t = None
        self.submit_tick = tick
        self.done_tick = None
        self.status = "queued"
        self.deadline_s = deadline_s
        self.queue_wait_s = 0.0
        self.prefill_active_s = 0.0
        self.handoff_s = 0.0
        self.backoff_s = 0.0
        self.decode_ticks = 0
        self.retries = 0
        self.prompt_tokens = prompt_tokens
        self.new_tokens = 0
        self.weights_version = 0
        self.canary = False
        self.lanes = []
        self.slot = None
        self.ttft_s = None
        self.drafted = 0
        self.accepted = 0


class TraceRecorder:
    """Records request-scoped and engine-level spans; see module docstring.

    Hooks are grouped by caller:

    - serving.py: ``request_submitted`` / ``request_granted`` /
      ``prefill_chunk`` / ``first_token`` / ``decode_tick`` /
      ``request_retry`` / ``quarantine`` / ``request_finished``
    - disagg.py: ``handoff`` / ``handoff_retry`` / ``handoff_flush`` /
      ``handoff_insert`` and the generic ``begin``/``end`` pair for
      resize phases
    - publish.py / autoscale.py / telemetry.py: ``begin``/``end`` /
      ``instant`` / ``on_event``
    - chaos.py: ``attach_chaos`` wires ``FaultInjector.on_inject`` to
      ``on_fault`` so injections annotate the span they hit.
    """

    def __init__(self, config: Optional[TraceConfig] = None, *, hub=None):
        self.config = config or TraceConfig()
        self._spans: List[Span] = []
        self._seq = 0
        self._dropped = 0
        self._warned_drop = False
        # Per-request accounting for explain(); insertion-ordered so
        # eviction drops the oldest finished request first.
        self._requests: Dict[int, _ReqTrace] = {}
        # Open queued-span per request id (closed at grant/finish).
        self._open_req: Dict[int, Span] = {}
        # Stack of open engine-level spans (begin/end discipline) plus a
        # detached set for spans that outlive their begin scope (layout
        # drains, canary windows).
        self._stack: List[Span] = []
        self._open: Dict[int, Span] = {}
        self._flow_seq = 0
        # Pending chaos annotation: a fault drawn with no open engine
        # span annotates the *next* span recorded for its unit (the retry
        # or decode-tick span the fault manifests as).
        self._pending_fault: Optional[Dict[str, Any]] = None
        self._chaos_seed: Optional[int] = None
        self._counts: Dict[str, int] = {}
        # Prometheus exposition now lives on the unified MetricsHub
        # (profiler.py): one renderer, one naming scheme. The recorder
        # registers its own stats as the "tracing" provider plus a legacy
        # text block keeping the pre-hub accelerate_tpu_trace_* names as
        # aliases for one release.
        from .profiler import MetricsHub

        self.hub = hub if hub is not None else MetricsHub()
        self.hub.register_provider("tracing", self.stats, replace=True)
        self.hub.register_text(self._span_metric_lines)
        self.hub.alias("accelerate_tpu_trace_dropped_spans_total",
                       "accelerate_tpu_tracing_dropped_spans")
        self.hub.alias("accelerate_tpu_trace_requests",
                       "accelerate_tpu_tracing_requests")

    # ------------------------------------------------------------------
    # span plumbing
    # ------------------------------------------------------------------
    def _now(self) -> Optional[float]:
        return time.perf_counter() if self.config.wall_clock else None

    def _new_span(self, subsystem, name, kind, tick, *, tid=None,
                  request_id=None, t=None, attrs=None) -> Optional[Span]:
        if len(self._spans) >= self.config.max_spans:
            self._dropped += 1
            if not self._warned_drop:
                self._warned_drop = True
                logger.warning(
                    "TraceRecorder hit max_spans=%d; further spans are "
                    "dropped (counted in stats()['dropped_spans'])",
                    self.config.max_spans,
                )
            return None
        span = Span(self._seq, subsystem, name, kind, tid, request_id,
                    tick, t if t is not None else self._now(),
                    attrs if attrs is not None else {})
        self._seq += 1
        self._spans.append(span)
        self._counts[kind] = self._counts.get(kind, 0) + 1
        pending = self._pending_fault
        if pending is not None and subsystem != "chaos" and (
            request_id is None or pending.get("unit") in (0, request_id)
        ):
            span.attrs.update(injected=True, point=pending["point"],
                              kind=pending["kind"],
                              seed=pending.get("seed"))
            self._pending_fault = None
        return span

    def _touch_request(self, rid) -> Optional[_ReqTrace]:
        return self._requests.get(rid)

    def _evict_requests(self) -> None:
        while len(self._requests) > self.config.max_requests:
            for rid, rt in self._requests.items():
                if rt.done_t is not None:
                    del self._requests[rid]
                    break
            else:
                # All in flight: evict the oldest outright.
                del self._requests[next(iter(self._requests))]

    # ------------------------------------------------------------------
    # generic engine-level spans (resize/publish/checkpoint phases)
    # ------------------------------------------------------------------
    def begin(self, subsystem: str, name: str, tick: int, *, tid=None,
              request_id=None, detached: bool = False, **attrs) -> Optional[int]:
        """Open an engine-level span; returns a handle for :meth:`end`.

        ``detached=True`` keeps the span off the nesting stack so it can
        outlive its begin scope (e.g. a layout drain that ends ticks
        later) without being force-closed by an enclosing ``end``.
        """
        span = self._new_span(subsystem, name, "phase", tick, tid=tid,
                              request_id=request_id, attrs=attrs)
        if span is None:
            return None
        self._open[span.seq] = span
        if not detached:
            self._stack.append(span)
        return span.seq

    def end(self, handle: Optional[int], tick: int, **attrs) -> None:
        """Close a span opened by :meth:`begin`.

        Also force-closes any still-open *stacked* spans begun after it
        (abort paths unwind cleanly without per-phase bookkeeping).
        """
        if handle is None:
            return
        span = self._open.pop(handle, None)
        if span is None:
            return
        if span in self._stack:
            while self._stack and self._stack[-1].seq > span.seq:
                inner = self._stack.pop()
                self._open.pop(inner.seq, None)
                inner.end_tick = tick
                inner.t1 = self._now()
            if self._stack and self._stack[-1] is span:
                self._stack.pop()
        span.end_tick = tick
        span.t1 = self._now()
        if attrs:
            span.attrs.update(attrs)

    def instant(self, subsystem: str, name: str, tick: int, *, tid=None,
                request_id=None, **attrs) -> None:
        """Record a zero-duration span (events: quarantine, decisions...)."""
        self._new_span(subsystem, name, "instant", tick, tid=tid,
                       request_id=request_id, attrs=attrs)

    # ------------------------------------------------------------------
    # request lifecycle hooks (serving.py)
    # ------------------------------------------------------------------
    def request_submitted(self, rid: int, tick: int, t: Optional[float], *,
                          prompt_tokens: int, budget: int,
                          deadline_s: Optional[float] = None) -> None:
        rt = _ReqTrace(rid, tick, t, prompt_tokens, deadline_s)
        self._requests[rid] = rt
        self._evict_requests()
        span = self._new_span("serving", "queued", "queued", tick,
                              tid="queue", request_id=rid, t=t,
                              attrs={"prompt_tokens": prompt_tokens,
                                     "budget": budget})
        if span is not None:
            self._open_req[rid] = span

    def request_granted(self, rid: int, tick: int, t: Optional[float], *,
                        slot, lane, weights_version: int,
                        canary: bool) -> None:
        lane = _lane_id(lane)
        rt = self._touch_request(rid)
        if rt is not None:
            rt.admit_t = t
            if t is not None and rt.enqueue_t is not None:
                rt.queue_wait_s += t - rt.enqueue_t
            rt.status = "admitted"
            rt.weights_version = weights_version
            rt.canary = canary
            rt.slot = slot
            if lane is not None and lane not in rt.lanes:
                rt.lanes.append(lane)
        span = self._open_req.pop(rid, None)
        if span is not None:
            span.end_tick = tick
            span.t1 = t if t is not None else self._now()
            span.attrs.update(slot=slot, lane=lane,
                              weights_version=weights_version, canary=canary)

    def prefill_chunk(self, rid: int, tick: int, t0: Optional[float],
                      t1: Optional[float], *, size: int, valid: int,
                      lane, slot, index: int, final: bool) -> None:
        lane = _lane_id(lane)
        rt = self._touch_request(rid)
        if rt is not None and t0 is not None and t1 is not None:
            rt.prefill_active_s += t1 - t0
            if lane is not None and lane not in rt.lanes:
                rt.lanes.append(lane)
        span = self._new_span(
            "prefill", f"chunk[{size}]", "prefill_chunk", tick,
            tid=(f"lane {lane}" if lane is not None else f"slot {slot}"),
            request_id=rid, t=t0,
            attrs={"size": size, "valid": valid, "index": index,
                   "final": final, "lane": lane, "slot": slot})
        if span is not None:
            span.end_tick = tick
            span.t1 = t1

    def first_token(self, rid: int, tick: int, t: Optional[float]) -> None:
        rt = self._touch_request(rid)
        if rt is not None:
            rt.first_token_t = t
            if t is not None and rt.submit_t is not None:
                rt.ttft_s = t - rt.submit_t
            rt.status = "decoding"

    def decode_tick(self, tick: int, t0: Optional[float],
                    t1: Optional[float], *, weights_version: int,
                    occupancy: int, n_slots: int,
                    request_ids=(), drafted: int = 0,
                    accepted: int = 0) -> None:
        attrs = {"weights_version": weights_version,
                 "occupancy": occupancy, "n_slots": n_slots}
        if drafted:
            # Speculation attribution: how many draft tokens this tick's
            # single verify forward covered and how many survived.
            attrs["drafted"] = drafted
            attrs["accepted"] = accepted
        span = self._new_span(
            "decode", f"decode v{weights_version}", "decode_tick", tick,
            tid="decode", t=t0, attrs=attrs)
        if span is not None:
            span.end_tick = tick
            span.t1 = t1
        for rid in request_ids:
            rt = self._touch_request(rid)
            if rt is not None:
                rt.decode_ticks += 1

    def request_retry(self, rid: int, tick: int, *, reason: str,
                      attempt: int, t: Optional[float] = None) -> None:
        rt = self._touch_request(rid)
        if rt is not None:
            rt.retries = attempt
            rt.enqueue_t = t if t is not None else self._now()
            rt.status = "requeued"
        self.instant("serving", f"retry[{reason}]", tick, tid="queue",
                     request_id=rid, reason=reason, attempt=attempt)

    def quarantine(self, kind: str, unit, tick: int, *,
                   request_id=None, **attrs) -> None:
        self.instant("serving", f"quarantine[{kind}]", tick,
                     tid=f"{kind} {unit}", request_id=request_id,
                     unit=unit, **attrs)

    def request_finished(self, rid: int, tick: int, t: Optional[float], *,
                         status: str, new_tokens: int,
                         weights_version: int, drafted: int = 0,
                         accepted: int = 0) -> None:
        rt = self._touch_request(rid)
        if rt is not None:
            rt.done_t = t
            rt.done_tick = tick
            rt.status = status
            rt.new_tokens = new_tokens
            rt.weights_version = weights_version
            rt.drafted = drafted
            rt.accepted = accepted
            if drafted:
                self.instant("decode", "speculation", tick, tid="decode",
                             request_id=rid, drafted=drafted,
                             accepted=accepted,
                             rejected=drafted - accepted)
        # A request shed/failed while queued still holds an open span.
        span = self._open_req.pop(rid, None)
        if span is not None:
            span.end_tick = tick
            span.t1 = t if t is not None else self._now()
            span.attrs["status"] = status
        fin = self._new_span("serving", f"finish[{status}]", "finish", tick,
                             tid="queue", request_id=rid, t=t,
                             attrs={"status": status,
                                    "new_tokens": new_tokens,
                                    "weights_version": weights_version})
        if fin is not None:
            fin.t1 = fin.t0

    # ------------------------------------------------------------------
    # disagg hooks: handoff transfer + retries + insert flow
    # ------------------------------------------------------------------
    def handoff(self, rid: int, tick: int, t0: Optional[float],
                t1: Optional[float], *, lane, slot, nbytes: int,
                final: bool) -> Optional[int]:
        """KV handoff dispatched from a prefill lane; returns a flow id

        the engine threads to :meth:`handoff_insert` when the transfer
        lands in the decode cache, stitching the two sides in the
        Chrome export.
        """
        span = self._new_span(
            "handoff", "kv_handoff", "handoff", tick,
            tid=f"lane {lane}", request_id=rid, t=t0,
            attrs={"lane": lane, "slot": slot, "nbytes": nbytes,
                   "final": final})
        if span is None:
            return None
        span.end_tick = tick
        span.t1 = t1
        self._flow_seq += 1
        span.flow = self._flow_seq
        return self._flow_seq

    def handoff_retry(self, rid: int, tick: int, *, attempt: int,
                      backoff_s: float, lane,
                      measured_s: Optional[float] = None) -> None:
        """One handoff retry: ``backoff_s`` is the deterministic computed

        backoff (recorded in span attrs for the tick-domain trace);
        ``measured_s`` is the measured sleep wall charged to the
        request's backoff term (falls back to ``backoff_s``).
        """
        rt = self._touch_request(rid)
        if rt is not None:
            rt.backoff_s += measured_s if measured_s is not None else backoff_s
        span = self._new_span(
            "handoff", f"retry[{attempt}]", "handoff_retry", tick,
            tid=f"lane {lane}", request_id=rid,
            attrs={"attempt": attempt, "lane": lane,
                   "backoff_s": round(backoff_s, 9)})
        if span is not None:
            span.end_tick = tick
            span.t1 = self._now()

    def handoff_flush(self, rid: int, tick: int, t0: Optional[float],
                      t1: Optional[float]) -> None:
        """Final-chunk forced drain wall, charged to the handoff term."""
        rt = self._touch_request(rid)
        if rt is not None and t0 is not None and t1 is not None:
            rt.handoff_s += t1 - t0
        span = self._new_span("handoff", "flush", "handoff_flush", tick,
                              request_id=rid, t=t0, tid="drain", attrs={})
        if span is not None:
            span.end_tick = tick
            span.t1 = t1

    def handoff_insert(self, tick: int, *, slot, flow: Optional[int],
                       request_id=None, armed: bool = False) -> None:
        span = self._new_span(
            "decode", "kv_insert", "handoff_insert", tick,
            tid=f"slot {slot}", request_id=request_id,
            attrs={"slot": slot, "armed": armed})
        if span is not None:
            span.t1 = span.t0
            span.flow = flow

    # ------------------------------------------------------------------
    # chaos annotation
    # ------------------------------------------------------------------
    def attach_chaos(self, injector) -> None:
        """Wire a ``FaultInjector`` so every injection annotates the span

        it hits (``injected=true`` + point/kind/seed): if an engine-level
        span is open the annotation lands there, otherwise it is held for
        the next span recorded for the fault's unit (the retry or decode
        tick the fault manifests as). An instant chaos span is always
        recorded so injections are visible even when nothing absorbs them.
        """
        self._chaos_seed = getattr(injector, "seed", None)
        injector.on_inject = self.on_fault

    def on_fault(self, fault: Dict[str, Any]) -> None:
        try:
            info = {"point": fault.get("point"), "kind": fault.get("kind"),
                    "unit": fault.get("unit", 0), "seed": self._chaos_seed}
            tick = fault.get("tick", 0)
            self.instant("chaos", f"{info['point']}:{info['kind']}", tick,
                         tid="inject", injected=True,
                         point=info["point"], kind=info["kind"],
                         unit=info["unit"], seed=info["seed"])
            if self._stack:
                self._stack[-1].attrs.update(
                    injected=True, point=info["point"],
                    kind=info["kind"], seed=info["seed"])
            else:
                self._pending_fault = info
        except Exception:  # never let tracing break an injection site
            logger.exception("trace fault annotation failed")

    # ------------------------------------------------------------------
    # telemetry event forwarding (checkpoint/watchdog/publish records)
    # ------------------------------------------------------------------
    _EVENT_SUBSYSTEM = {
        "checkpoint_save": "checkpoint", "checkpoint_load": "checkpoint",
        "checkpoint_verify": "checkpoint",
        "checkpoint_save_retry": "checkpoint",
        "checkpoint_torn_skipped": "checkpoint",
        "preemption_save": "checkpoint", "rollback": "checkpoint",
        "checkpoint_fallback_save": "checkpoint",
        "checkpoint_async_error": "checkpoint",
        "training_stalled": "watchdog",
        "weights_published": "publish",
    }

    def on_event(self, event: str, fields: Dict[str, Any],
                 tick: int = 0) -> None:
        """Forward a telemetry ``record_event`` into the trace.

        Events with a ``seconds``-like duration become spans with that
        wall duration; the rest are instants. This is how checkpoint
        save/restore and watchdog stalls get spans without every caller
        growing a tracing kwarg.
        """
        subsystem = self._EVENT_SUBSYSTEM.get(event)
        if subsystem is None:
            return
        dur = None
        for key in ("seconds", "save_s", "load_s", "wall_s", "verify_s"):
            val = fields.get(key)
            if isinstance(val, (int, float)):
                dur = float(val)
                break
        attrs = {k: v for k, v in fields.items()
                 if isinstance(v, (int, float, str, bool)) and k != "time"}
        span = self._new_span(subsystem, event,
                              "event" if dur is None else "phase",
                              tick, tid=subsystem, attrs=attrs)
        if span is not None and dur is not None and span.t0 is not None:
            # The event is recorded *after* the work; backdate the start.
            span.t0 = span.t0 - dur
            span.t1 = span.t0 + dur

    # ------------------------------------------------------------------
    # consumer 1: explain(request_id)
    # ------------------------------------------------------------------
    def explain(self, request_id: int) -> Dict[str, Any]:
        """Critical-path SLO attribution for one request.

        Decomposes the measured TTFT (``first_token_t - submit_t``) into:

        - ``queue_wait_s``: submitted/requeued -> granted a slot
        - ``prefill_s``: chunk dispatch walls minus handoff/backoff
        - ``handoff_s``: KV handoff final-flush walls (disagg only)
        - ``backoff_s``: chaos-retry backoff sleeps (handoff retries)
        - ``stall_s``: the remainder — granted but not dispatching
          (prefill rotation across ticks, decode interleave, drain
          stalls during a resize)

        All five are disjoint sub-intervals of the TTFT window, so
        ``sum(terms) == ttft_s`` within float tolerance by construction
        (pinned by test). ``decode_s`` (first token -> done) is reported
        alongside but is not a TTFT term.
        """
        rt = self._requests.get(request_id)
        if rt is None:
            raise KeyError(f"request {request_id} not traced")
        n_spans = sum(1 for s in self._spans if s.request_id == request_id)
        out: Dict[str, Any] = {
            "request_id": request_id,
            "status": rt.status,
            "retries": rt.retries,
            "prompt_tokens": rt.prompt_tokens,
            "new_tokens": rt.new_tokens,
            "weights_version": rt.weights_version,
            "canary": rt.canary,
            "lanes": list(rt.lanes),
            "slot": rt.slot,
            "decode_ticks": rt.decode_ticks,
            "n_spans": n_spans,
            "ttft_s": rt.ttft_s,
            "terms": None,
            "dominant": None,
            "decode_s": None,
            "total_s": None,
            "deadline_s": rt.deadline_s,
            "deadline_missed": None,
            # Speculation is a decode-phase property, not a TTFT term:
            # accepted drafts shorten decode_s, never the TTFT window.
            "speculation": (
                {"drafted": rt.drafted, "accepted": rt.accepted,
                 "rejected": rt.drafted - rt.accepted}
                if rt.drafted else None
            ),
        }
        if rt.first_token_t is not None and rt.submit_t is not None:
            ttft = rt.first_token_t - rt.submit_t
            handoff = rt.handoff_s
            backoff = rt.backoff_s
            prefill = rt.prefill_active_s - handoff - backoff
            stall = ttft - rt.queue_wait_s - rt.prefill_active_s
            terms = {
                "queue_wait_s": rt.queue_wait_s,
                "prefill_s": prefill,
                "handoff_s": handoff,
                "backoff_s": backoff,
                "stall_s": stall,
            }
            out["ttft_s"] = ttft
            out["terms"] = terms
            out["dominant"] = max(terms, key=lambda k: terms[k])
        if rt.done_t is not None and rt.submit_t is not None:
            out["total_s"] = rt.done_t - rt.submit_t
            if rt.first_token_t is not None:
                out["decode_s"] = rt.done_t - rt.first_token_t
            if rt.deadline_s is not None:
                out["deadline_missed"] = out["total_s"] > rt.deadline_s
        return out

    # ------------------------------------------------------------------
    # consumer 2: Chrome trace (Perfetto) export
    # ------------------------------------------------------------------
    def chrome_trace(self) -> Dict[str, Any]:
        """Build the Chrome trace JSON object (see ``export_chrome_trace``)."""
        events: List[Dict[str, Any]] = []
        # Stable pid per subsystem, stable tid per (pid, thread-name).
        tids: Dict[tuple, int] = {}
        seen_pids: Dict[str, int] = {}
        extra_pid = max(_PIDS.values())

        def pid_of(subsystem: str) -> int:
            pid = _PIDS.get(subsystem)
            if pid is None:
                pid = seen_pids.get(subsystem)
                if pid is None:
                    nonlocal extra_pid
                    extra_pid += 1
                    pid = seen_pids[subsystem] = extra_pid
            return pid

        def tid_of(pid: int, name: Optional[str]) -> int:
            key = (pid, name or "main")
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len([k for k in tids if k[0] == pid]) + 1
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": name or "main"}})
            return tid

        for subsystem, pid in sorted(_PIDS.items(), key=lambda kv: kv[1]):
            events.append({"ph": "M", "pid": pid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": subsystem}})

        # Wall timestamps are relative to the first recorded wall so the
        # trace starts near t=0; spans without walls fall back to the
        # tick clock at 1 ms/tick so tick-only traces still render.
        base = min((s.t0 for s in self._spans if s.t0 is not None),
                   default=None)

        def ts_us(span: Span) -> tuple:
            if span.t0 is not None and base is not None:
                t0 = (span.t0 - base) * 1e6
                t1 = ((span.t1 - base) * 1e6
                      if span.t1 is not None else t0)
            else:
                t0 = span.start_tick * 1000.0
                t1 = span.end_tick * 1000.0
            return t0, max(t1 - t0, 0.0)

        for span in self._spans:
            pid = pid_of(span.subsystem)
            tid = tid_of(pid, span.tid)
            ts, dur = ts_us(span)
            args = {k: v for k, v in span.attrs.items()}
            if span.request_id is not None:
                args["request_id"] = span.request_id
            args["tick"] = span.start_tick
            ev = {"ph": "X", "pid": pid, "tid": tid, "name": span.name,
                  "cat": span.subsystem, "ts": round(ts, 3),
                  "dur": round(max(dur, 1.0), 3), "args": args}
            events.append(ev)
            if span.flow is not None:
                # Flow start at the producing side (handoff span on the
                # prefill lane), flow finish at the consuming side
                # (kv_insert on the decode slot). bp="e" binds the
                # finish to the enclosing slice.
                ph = "s" if span.kind == "handoff" else "f"
                flow_ev = {"ph": ph, "pid": pid, "tid": tid,
                           "name": "kv_handoff", "cat": "handoff",
                           "id": span.flow, "ts": round(ts, 3)}
                if ph == "f":
                    flow_ev["bp"] = "e"
                events.append(flow_ev)
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {"producer": "accelerate_tpu.tracing",
                              "spans": len(self._spans),
                              "dropped_spans": self._dropped}}

    def export_chrome_trace(self, path: str) -> str:
        """Write a Perfetto-loadable Chrome trace JSON to ``path``.

        Load it at https://ui.perfetto.dev (or chrome://tracing):
        pid=subsystem (serving/prefill/handoff/decode/...), tid=lane or
        slot, with flow arrows stitching each KV handoff from its
        prefill lane to the decode slot it lands in.
        """
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        return path

    # ------------------------------------------------------------------
    # consumer 3: Prometheus text exposition (delegated to the MetricsHub)
    # ------------------------------------------------------------------
    def register_gauges(self, subsystem: str,
                        provider: Callable[[], Dict[str, Any]]) -> None:
        """Register a live stats provider (e.g. ``engine.stats``) whose

        numeric leaves are exposed by :meth:`metrics_text` as
        ``accelerate_tpu_<subsystem>_<path>`` gauges — same numbers as
        ``stats()``/``window_stats()``, scraper-friendly format. Delegates
        to :meth:`MetricsHub.register_provider` (the single registry);
        last registration wins, preserving the pre-hub semantics for
        engines that replace a predecessor in the same process.
        """
        self.hub.register_provider(subsystem, provider, replace=True)

    @staticmethod
    def _sanitize(name: str) -> str:
        return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

    def _span_metric_lines(self) -> List[str]:
        """Per-kind span counters for the hub renderer: the canonical
        ``accelerate_tpu_tracing_spans_total{kind=...}`` series plus the
        pre-hub ``accelerate_tpu_trace_spans_total`` spelling, kept as an
        alias for one release (the hub's alias warning covers it)."""
        lines = [
            "# HELP accelerate_tpu_tracing_spans_total spans recorded by kind",
            "# TYPE accelerate_tpu_tracing_spans_total counter",
        ]
        for kind in sorted(self._counts):
            lines.append(
                f'accelerate_tpu_tracing_spans_total{{kind="{self._sanitize(kind)}"}} '
                f"{self._counts[kind]}")
        for kind in sorted(self._counts):
            lines.append(
                f'accelerate_tpu_trace_spans_total{{kind="{self._sanitize(kind)}"}} '
                f"{self._counts[kind]}")
        return lines

    def metrics_text(self) -> str:
        """Prometheus snapshot — now rendered by the unified
        :class:`~accelerate_tpu.profiler.MetricsHub` (``self.hub``), so
        every exporter shares one renderer and one naming scheme."""
        return self.hub.render()

    # ------------------------------------------------------------------
    # deterministic projection + bookkeeping
    # ------------------------------------------------------------------
    def tick_trace(self) -> List[Dict[str, Any]]:
        """Deterministic tick-domain projection of every span.

        Contains no wall clocks; for a tick-driven seeded workload two
        runs produce bit-identical JSON (``json.dumps(tick_trace())``) —
        the invariant ``make trace-smoke`` pins.
        """
        return [s.tick_view() for s in self._spans]

    def spans(self, request_id: Optional[int] = None) -> List[Span]:
        if request_id is None:
            return list(self._spans)
        return [s for s in self._spans if s.request_id == request_id]

    def request_ids(self) -> List[int]:
        return list(self._requests)

    def stats(self) -> Dict[str, Any]:
        """Summary block (embedded in ``telemetry.summary()["tracing"]``)."""
        return {
            "spans": len(self._spans),
            "dropped_spans": self._dropped,
            "by_kind": dict(sorted(self._counts.items())),
            "requests": len(self._requests),
            "open_spans": len(self._open) + len(self._open_req),
            "flows": self._flow_seq,
        }

    def reset(self) -> None:
        """Drop all spans and request accounting (warmup boundary: the

        engines call this from ``reset_metrics()`` so the measured
        window starts with a clean, tick-zeroed trace)."""
        self._spans.clear()
        self._seq = 0
        self._dropped = 0
        self._warned_drop = False
        self._requests.clear()
        self._open_req.clear()
        self._stack.clear()
        self._open.clear()
        self._flow_seq = 0
        self._pending_fault = None
        self._counts.clear()
