"""Imperative-surface optimizer wrapper (layer L4).

Reference: src/accelerate/optimizer.py:38-213 — ``AcceleratedOptimizer`` no-ops
``step``/``zero_grad`` during gradient accumulation and runs the DP all-reduce
before stepping. Here the wrapped object is an ``optax.GradientTransformation``
and the canonical state lives in the :class:`~accelerate_tpu.train_state.TrainState`
held by the Accelerator; ``step()`` applies the accumulated gradients through a
jitted update whose in/out shardings keep everything on the mesh. The DP
gradient mean needs no explicit all-reduce: gradients come out of the jitted
backward already psum'd by GSPMD.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .state import AcceleratorState, GradientState


class AcceleratedOptimizer:
    def __init__(self, optimizer, device_placement: bool = True, scaler=None, accelerator=None):
        self.optimizer = optimizer  # the optax GradientTransformation
        self.scaler = scaler
        self.accelerator_state = AcceleratorState()
        self.gradient_state = GradientState()
        self.device_placement = device_placement
        self._accelerator = accelerator
        # Which TrainState slot this optimizer's tx was bound to (multi-model
        # prepare); None/0 = the primary. The imperative step() path only
        # serves the primary — non-primary models step through
        # accelerator.prepare_train_step(loss_fn, model=...).
        self._state_slot: Optional[int] = None
        self._is_overflow = False
        self._accumulated: Optional[Any] = None
        self._micro_count = 0
        self._apply_jit = None

    # -- reference surface -------------------------------------------------

    @property
    def state(self):
        if self._accelerator is not None:
            states = getattr(self._accelerator, "_train_states", None)
            slot = self._state_slot or 0
            if states and slot < len(states):
                return states[slot].opt_state
        return None

    @property
    def param_groups(self):
        """Minimal param_groups view for reference-parity introspection."""
        lr = None
        if self._accelerator is not None and self._accelerator._scheduler is not None:
            lr = self._accelerator._scheduler.get_last_lr()
        if lr is None:
            # Schedule embedded in the optax chain (inject_hyperparams):
            # read the live lr straight from opt_state.
            from .scheduler import extract_lr_info

            lr = extract_lr_info(self.state).get("lr")
        return [{"params": [], "lr": lr}]

    def zero_grad(self, set_to_none: bool = True):
        """Drop the accumulation buffer. No-op mid-accumulation like the
        reference (optimizer.py:112-124)."""
        if self.gradient_state.sync_gradients:
            self._accumulated = None
            self._micro_count = 0

    def accumulate_grads(self, grads):
        """Called by ``Accelerator.backward`` with freshly computed grads."""
        if self._accumulated is None:
            self._accumulated = grads
        else:
            self._accumulated = jax.tree.map(jnp.add, self._accumulated, grads)
        self._micro_count += 1

    @property
    def grads(self):
        return self._accumulated

    def step(self, closure=None):
        """Apply accumulated grads when on a sync boundary; no-op otherwise
        (reference: optimizer.py:145-177)."""
        if not self.gradient_state.sync_gradients:
            return
        if self._accelerator is None:
            raise RuntimeError(
                "This AcceleratedOptimizer is not bound to an Accelerator; "
                "pass it through `accelerator.prepare(...)` first."
            )
        if self._state_slot not in (None, 0):
            raise NotImplementedError(
                "The imperative backward()/optimizer.step() surface serves the "
                "primary (first-prepared) model only. Step additional models "
                "through accelerator.prepare_train_step(loss_fn, model=...)."
            )
        if self._accumulated is None:
            return
        self._is_overflow = not self._accelerator._apply_gradients(self._accumulated)
        self._accumulated = None
        self._micro_count = 0

    @property
    def step_was_skipped(self) -> bool:
        """True when the last step overflowed under fp16 loss scaling
        (reference: optimizer.py:199-204)."""
        return self._is_overflow

    def train(self):
        pass

    def eval(self):
        pass

    def __getstate__(self):
        return self.__dict__.copy()

    def __setstate__(self, state):
        self.__dict__.update(state)
