"""User-facing model bundle.

The reference's ``prepare_model`` wraps a torch ``nn.Module`` in place
(reference: accelerator.py:1769-2066). JAX separates architecture (pure apply
function) from state (param pytree); :class:`Model` is the thin bundle that
carries both through ``Accelerator.prepare`` so the user-visible flow keeps
the reference's shape::

    model = Model.from_flax(module, rng, sample_batch)     # or Model(apply_fn, params)
    model, optimizer, loader = accelerator.prepare(model, tx, loader)
    logits = model(batch)                                   # eval/inference call

After prepare, ``model.params`` is a view onto the accelerator's canonical
sharded TrainState — the same single-source-of-truth rule the reference
enforces by mutating the module in place.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np


class Model:
    def __init__(
        self,
        apply_fn: Callable = None,
        params: Any = None,
        extra_state: Any = None,
        module: Any = None,
        tp_rules: Optional[list] = None,
    ):
        if apply_fn is None and module is None:
            raise ValueError("Provide apply_fn or module")
        self.module = module
        if apply_fn is None:
            apply_fn = module.apply
        self.apply_fn = apply_fn
        self._params = params
        self.extra_state = extra_state
        # Optional tensor-parallel rule table: [(name_regex, PartitionSpec)].
        self.tp_rules = tp_rules or list(getattr(module, "tp_rules", []) or [])
        self._accelerator = None
        self._accelerate_prepared = False

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_flax(cls, module, rng, *sample_args, tp_rules=None, **sample_kwargs) -> "Model":
        """Initialize a flax.linen module and bundle it."""
        variables = module.init(rng, *sample_args, **sample_kwargs)
        variables = dict(variables)
        params = variables.pop("params")
        extra = variables or None
        return cls(module=module, params=params, extra_state=extra, tp_rules=tp_rules)

    # -- state access ----------------------------------------------------

    def _bound_state(self):
        """This model's TrainState slot on its Accelerator, or None. Each
        prepared model owns one slot (multi-model training: GAN/distillation);
        _state_slot is assigned by Accelerator._prepare_state."""
        acc = self._accelerator
        if acc is None:
            return None
        states = getattr(acc, "_train_states", None)
        if not states:
            return None
        slot = getattr(self, "_state_slot", 0) or 0
        return states[slot] if slot < len(states) else None

    @property
    def params(self):
        state = self._bound_state()
        if state is not None:
            return state.params
        return self._params

    @params.setter
    def params(self, value):
        state = self._bound_state()
        if state is not None:
            slot = getattr(self, "_state_slot", 0) or 0
            self._accelerator._train_states[slot] = state.replace(params=value)
        else:
            self._params = value

    def parameters(self):
        """torch-parity iterator over param leaves."""
        return iter(jax.tree.leaves(self.params))

    def num_parameters(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))

    def state_dict(self):
        from .utils.other import flatten_state_dict

        return flatten_state_dict(self.params)

    def load_state_dict(self, flat: dict):
        from .utils.other import unflatten_state_dict

        tree = unflatten_state_dict({k: v for k, v in flat.items()})
        # Re-map by name into the existing structure to preserve treedef/dtypes.
        current = self.params

        def _remap(path_tree, new_tree):
            if isinstance(path_tree, dict):
                return {k: _remap(v, new_tree.get(k)) for k, v in path_tree.items()}
            if new_tree is None:
                raise KeyError("Missing key in loaded state dict")
            import jax.numpy as jnp

            return jnp.asarray(new_tree, dtype=path_tree.dtype).reshape(path_tree.shape)

        self.params = _remap(current, tree)

    # -- forward ---------------------------------------------------------

    def __call__(self, *args, rngs=None, train: bool = False, **kwargs):
        # Live view: after jitted steps (which donate the old buffers) this
        # model's slot on the accelerator holds the current params.
        state = self._bound_state()
        if state is not None:
            params, extra = state.params, state.extra_state
        else:
            params, extra = self._params, self.extra_state
        variables = {"params": params}
        if extra:
            variables.update(extra)
        call_kwargs = dict(kwargs)
        if rngs is not None:
            call_kwargs["rngs"] = rngs
        if not train:
            # Inference: fp8 recipes with use_during_eval=False (the default)
            # trace their matmuls in full precision (ops/fp8.py eval_mode).
            from .ops.fp8 import eval_mode

            with eval_mode():
                return self.apply_fn(variables, *args, **call_kwargs)
        return self.apply_fn(variables, *args, **call_kwargs)

    def eval(self):
        return self

    def train(self, mode: bool = True):
        return self
