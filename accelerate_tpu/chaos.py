"""Deterministic fault injection for the serving AND training stacks.

Production characterizations of distributed DL deployments show failure
behavior under load — not peak throughput — dominates deployed performance
(arXiv:2505.12832, PAPERS.md). The serving engines carry a request-lifecycle
robustness layer (admission control, retries, lane quarantine, degraded
fallback — serving.py / disagg.py) and the training loop carries its own
(atomic checkpoints, divergence rollback, preemption resume, the step
watchdog — fault_tolerance.py); THIS module is how both get exercised: a
seed-driven :class:`FaultInjector` whose schedule is **fully determined by
``(seed, injection_point, tick, unit)``** — no wall-clock, no global RNG —
so a chaos run replays exactly, twice, anywhere. The hash is keyed by the
point NAME, so adding points never moves an existing schedule.

Serving injection points (registered by the engines at the four places a
real deployment fails):

- ``prefill_dispatch`` — the jitted prefill chunk dispatch (colocated slot
  write, or a disagg lane's private cache write);
- ``decode_tick`` — the steady-state decode step (poisons a live slot's KV
  page so the nonfinite-logits sentinel path runs);
- ``handoff_device_put`` — the disagg KV-page transfer to the decode mesh;
- ``lane_health`` — a prefill lane's liveness check at dispatch.

Serving fault kinds:

- ``transfer_error`` — a raised transfer/dispatch error (``u < 0.75``:
  transient, one failed attempt; else persistent — every retry fails, which
  is how the lane-quarantine path gets coverage without a scheduled fault);
- ``delay`` — a straggler handoff: the page's background insert is deferred
  ``delay_ticks`` ticks (forced drains — depth overflow, the final-chunk
  flush — still complete it, exactly like awaiting a slow async transfer);
- ``dead_lane`` — the lane is dead: dispatch raises and the engine
  quarantines it;
- ``poison`` — a nonfinite (NaN) KV page: the transferred page (or, at
  ``decode_tick``, a live slot's page in place) is overwritten with NaN,
  which the decode-side sentinel must catch;
- ``bit_flip`` (at ``decode_tick``) — silent data corruption: the emitted
  token for one live slot is XOR'd with 1 after the host fetch —
  wrong-but-finite, invisible to every NaN sentinel, caught only by the
  serving decode canary's bit-wise golden comparison (sdc.py).

Training injection points (drawn by the fault-tolerance manager when a
``FaultToleranceKwargs(chaos=...)`` handler arms it — fault_tolerance.py):

- ``train_step`` — after each prepared step's lagged metric fetch
  (``tick`` = monotonic observe count, ``unit`` = process index);
- ``collective_op`` — before the watchdog's gang-heartbeat collective;
- ``checkpoint_save`` — inside the save-retry loop (``tick`` = save index,
  ``unit`` = attempt, so a torn first attempt retries clean);
- ``dataloader_batch`` — at the loader's device_put boundary;
- ``host_heartbeat`` — the per-step host liveness draw.

Training fault kinds:

- ``nonfinite_grad`` — the metrics the divergence sentinel sees turn NaN
  (model state untouched, so a rollback replay stays bit-equal);
- ``slow_step`` — a deterministic host-side delay (``slow_step_s`` seconds,
  or the schedule entry's ``seconds``) — the straggler the watchdog must
  name;
- ``torn_write`` — the checkpoint save attempt raises, driving the
  retry/backoff → fallback-dir path;
- ``corrupt_batch`` — the batch is NaN-poisoned at the device boundary, so
  a REAL divergence flows through sentinel → rollback;
- ``dead_host`` — the process exits with a chosen code (schedule entry's
  ``exit_code``, default :data:`DEAD_HOST_DEFAULT_EXIT_CODE`), driving the
  launch supervisor's classify → backoff → relaunch path;
- ``bit_flip`` (at ``train_step``) — silent data corruption: the
  host-observed integrity digest on the targeted rank goes wrong-but-finite
  (``Fault.extra``: ``mode`` = ``"transient"`` | ``"sticky"``, optional
  ``rank``/``leaf``). Only the SDC sentinel's cross-replica vote (sdc.py)
  can see it; ``sticky`` also fails the redundant-compute probe, convicting
  the silicon → ``SDC_EXIT_CODE`` quarantine + shrink-relaunch.

Publication injection points (drawn by ``publish.WeightPublisher`` when
constructed with ``chaos=...``):

- ``publish_manifest`` — the checkpoint-manifest verification gate
  (``tick`` = publish attempt index, ``unit`` = weights_version);
  ``torn_write`` makes the manifest read as torn and ``version_mismatch``
  as stale — either way the checkpoint is skipped and the old version
  keeps serving;
- ``publish_transfer`` — the train→serve weight redistribution
  (``transfer_error``: ``u < 0.75`` transient — one retry heals it — else
  persistent, exhausting the retry budget and aborting the publish);
- ``canary_window`` — the canary promote/rollback decision
  (``slo_regression`` forces the decision to read as a regression, driving
  the bit-equal auto-rollback path).

Autoscaling injection points (drawn by ``autoscale.AutoscaleController``
and the disagg router's live resize when constructed with ``chaos=...``):

- ``autoscale_decide`` — the per-sample scaling decision (``tick`` = the
  engine tick the sample was taken at); ``flap`` inverts that one sample's
  hysteresis-band reading, so only the consecutive-breach damper stands
  between one noisy sample and a spurious resize;
- ``resize_transfer`` — the old→new layout param redistribution inside
  ``DisaggServingEngine.resize`` (``tick`` = resize sequence number;
  ``transfer_error``: ``u < 0.75`` transient — one retry heals it — else
  persistent, exhausting the retry budget and aborting the resize with the
  old layout untouched; ``delay`` adds a backoff-shaped stall);
- ``load_spike`` — a synthetic load spike at sampling time (``spike``
  inflates the sample's queue-depth/shed signals, exercising the grow path
  without needing real overload in a smoke).

Crash-durability injection points (drawn by the request journal and the
engines' hard-crash path — journal.py / serving.py):

- ``journal_append`` — one WAL append (``tick`` = engine tick, ``unit`` =
  request id); ``torn_write`` tears the line mid-record, then the journal
  re-writes it whole — replay's checksum-skip path runs while durability
  holds;
- ``journal_compact`` — the sealed-segment merge; ``torn_write`` aborts the
  compaction cleanly (staging removed, sealed segments untouched);
- ``engine_crash`` — the end-of-tick process-death draw (``crash``): the
  engine flushes telemetry + this injector's log (:func:`flush_injected_log`
  — the post-mortem schedule is never torn), then hard-exits with
  :data:`~accelerate_tpu.utils.constants.SERVING_CRASH_EXIT_CODE` (or the
  schedule entry's ``exit_code``), driving the supervisor's
  serving-crash → zero-backoff relaunch → journal recovery path.

Off by default everywhere: no injector exists unless you construct one and
pass it to an engine (``ServingEngine(..., chaos=...)``) or to
``FaultToleranceKwargs(chaos=...)``; the import is lazy-safe (numpy only)
and the hot paths hold a single ``is None`` check per site.

Usage::

    from accelerate_tpu import FaultInjector, ServingConfig

    chaos = FaultInjector(
        seed=7,
        rates={"handoff_device_put": {"transfer_error": 0.05}},
        schedule=[{"point": "lane_health", "kind": "dead_lane", "unit": 0}],
    )
    engine = DisaggServingEngine(model, cfg, disagg=dc, chaos=chaos)
    engine.run(prompts)
    chaos.injected        # the exact (tick, point, kind, unit) log — replay
                          # with the same seed and it is identical
"""

from __future__ import annotations

import logging
import zlib
from typing import Callable, NamedTuple, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "Fault",
    "FaultInjector",
    "InjectedFaultError",
    "INJECTION_POINTS",
    "FAULT_KINDS",
    "DEAD_HOST_DEFAULT_EXIT_CODE",
    "deterministic_jitter",
    "flush_injected_log",
]

INJECTION_POINTS = (
    # serving (PR 9)
    "prefill_dispatch",
    "decode_tick",
    "handoff_device_put",
    "lane_health",
    # training (fault_tolerance.py hooks)
    "train_step",
    "collective_op",
    "checkpoint_save",
    "dataloader_batch",
    "host_heartbeat",
    # weight publication (publish.py)
    "publish_manifest",
    "publish_transfer",
    "canary_window",
    # autoscaling (autoscale.py + the disagg live resize)
    "autoscale_decide",
    "resize_transfer",
    "load_spike",
    # crash-durable serving (journal.py + the engines' hard-crash path)
    "journal_append",
    "journal_compact",
    "engine_crash",
    # fleet routing (fleet.py): whole-cell death, partition, heartbeat loss
    "cell_crash",
    "cell_partition",
    "router_heartbeat",
    # speculative decoding + quantized KV pages (serving.py / disagg.py)
    "draft_mismatch",
    "page_dequant",
)

FAULT_KINDS = (
    "transfer_error", "delay", "dead_lane", "poison",
    "nonfinite_grad", "slow_step", "torn_write", "corrupt_batch", "dead_host",
    "slo_regression", "version_mismatch", "flap", "spike", "crash",
    "bit_flip",
)

# An injected dead host exits 139 (128 + SIGSEGV) unless the schedule entry
# picks another code: the supervisor's classifier reads 128+signal codes as
# hardware-ish death, distinct from a clean deterministic crash.
DEAD_HOST_DEFAULT_EXIT_CODE = 139

# Which kinds make sense where — rates naming other combos are rejected at
# construction so a typo'd chaos spec fails loudly, not silently-never-fires.
_POINT_KINDS = {
    "prefill_dispatch": ("transfer_error",),
    # decode_tick bit_flip (sdc.py): the emitted token for one live slot is
    # XOR'd with 1 after the host fetch — wrong-but-finite output the decode
    # canary must catch bit-wise (NaN sentinels never see it).
    "decode_tick": ("poison", "bit_flip"),
    "handoff_device_put": ("transfer_error", "delay", "poison"),
    "lane_health": ("dead_lane",),
    # train_step bit_flip (sdc.py): the host-observed integrity digest on the
    # targeted rank is corrupted — finite, so only cross-replica voting sees
    # it. ``Fault.extra`` carries ``mode`` ("transient"|"sticky") and
    # optionally ``rank``/``leaf``; sticky also trips the redundant-compute
    # probe, convicting the silicon (SDC_EXIT_CODE).
    "train_step": ("nonfinite_grad", "slow_step", "bit_flip"),
    "collective_op": ("slow_step",),
    "checkpoint_save": ("torn_write",),
    "dataloader_batch": ("corrupt_batch",),
    "host_heartbeat": ("dead_host",),
    # Weight publication (publish.py): a torn/mismatched manifest skips the
    # checkpoint (old version keeps serving), a transfer error drives the
    # retry/backoff -> abort-publish path, and an injected SLO regression
    # forces the canary decision to roll back.
    "publish_manifest": ("torn_write", "version_mismatch"),
    "publish_transfer": ("transfer_error",),
    "canary_window": ("slo_regression",),
    # Autoscaling (autoscale.py): a flap inverts one sample's band reading
    # (the consecutive-breach damper must absorb it), a spike inflates one
    # sample's load signals, and a resize transfer_error/delay drives the
    # live resize's retry/backoff -> clean-abort path.
    "autoscale_decide": ("flap",),
    "resize_transfer": ("transfer_error", "delay"),
    "load_spike": ("spike",),
    # Crash-durable serving (journal.py): a torn journal append is re-written
    # whole after the detected short write (the replay-side checksum-skip path
    # gets coverage), a torn compaction aborts cleanly with the sealed
    # segments untouched, and an engine_crash hard-exits the serving process
    # (SERVING_CRASH_EXIT_CODE, or the entry's ``exit_code``) after flushing
    # telemetry + this injector's log — the supervisor relaunch + journal
    # recovery path.
    "journal_append": ("torn_write",),
    "journal_compact": ("torn_write",),
    "engine_crash": ("crash",),
    # Fleet routing (fleet.py): a cell_crash hard-kills an entire cell
    # mid-trace (its engine is abandoned, journal unsealed — the router's
    # exactly-once cross-cell drain path), a cell_partition makes a cell
    # unreachable from the router for ``Fault.extra["delay_ticks"]`` ticks
    # (degraded: it keeps ticking, takes no new admissions, its finished
    # rows surface when the partition heals), and a router_heartbeat delay
    # skips one health-reclassification pass (stale states for a tick).
    "cell_crash": ("crash",),
    "cell_partition": ("delay",),
    "router_heartbeat": ("delay",),
    # Speculative decoding (serving.py): a draft_mismatch poison wipes one
    # decoding slot's n-gram history (-1 fill), collapsing its acceptance
    # rate to the floor — output must stay bit-equal, only throughput and
    # the acceptance telemetry move (the verifiable property).
    "draft_mismatch": ("poison",),
    # Quantized KV pages (disagg.py): a page_dequant poison NaNs the
    # handed-off page's dequant scales, so the decode side's in-kernel
    # dequantize propagates NaN into attention — the existing poison-slot
    # quarantine/retry machinery must catch it.
    "page_dequant": ("poison",),
}

_MASK = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """The splitmix64 finalizer — the counter-based PRNG core that makes a
    draw a pure function of its inputs."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return (z ^ (z >> 31)) & _MASK


def _u01(*parts) -> float:
    """Uniform in [0, 1) from an arbitrary (seed, str/int, ...) tuple —
    deterministic across processes and platforms (no hash randomization:
    strings go through crc32)."""
    h = 0
    for p in parts:
        if isinstance(p, str):
            p = zlib.crc32(p.encode("utf-8"))
        h = _splitmix64((h ^ (int(p) & _MASK)) & _MASK)
    return h / float(1 << 64)


def deterministic_jitter(seed: int, tick: int, attempt: int) -> float:
    """Jitter factor in [0.5, 1.0) for retry backoff — deterministic in its
    inputs so a chaos replay backs off identically."""
    return 0.5 + 0.5 * _u01(seed, "backoff", tick, attempt)


def flush_injected_log(injector, telemetry) -> None:
    """Hard-exit hygiene, shared by every injected process death (serving's
    ``engine_crash`` and training's ``dead_host``): push the injector's full
    ``injected`` log through the telemetry recorder AND close it before
    ``os._exit``, so the post-mortem fault schedule is never torn. Best
    effort on every edge — a dying process must still die."""
    if telemetry is not None:
        if injector is not None:
            try:
                telemetry.record_event(
                    "chaos_injected_log", seed=injector.seed,
                    injected=list(injector.injected),
                    summary=injector.summary(),
                )
            except Exception:  # pragma: no cover - dying anyway
                logger.exception("chaos: injected-log flush failed")
            prof = getattr(telemetry, "profiler", None)
            if prof is not None:
                # The flight bundle (profiler.py) carries the fault
                # schedule that killed the run next to the last attribution
                # records — the dump itself happens at the exit site.
                try:
                    prof.note_gauge("chaos", {
                        "seed": injector.seed,
                        "injected": injector.summary().get("injected"),
                        "last": (list(injector.injected)[-3:]
                                 if injector.injected else []),
                    })
                except Exception:  # pragma: no cover - dying anyway
                    pass
        try:
            telemetry.close()
        except Exception:  # pragma: no cover - dying anyway
            pass


class Fault(NamedTuple):
    """One drawn fault. ``u`` is the residual uniform the engine uses for
    deterministic sub-decisions (e.g. transient vs persistent transfer
    errors) without another RNG. ``extra`` carries a schedule entry's
    pass-through fields (``seconds`` for ``slow_step``, ``exit_code`` for
    ``dead_host``); rate-driven faults leave it None."""

    point: str
    kind: str
    tick: int
    unit: int
    u: float
    extra: Optional[dict] = None


class InjectedFaultError(RuntimeError):
    """Raised at an injection site to model a transfer/dispatch failure.
    Subclasses RuntimeError so the engines' recovery paths treat injected
    and real (XLA runtime) failures identically."""

    def __init__(self, fault: Fault):
        super().__init__(
            f"injected {fault.kind} at {fault.point} "
            f"(tick {fault.tick}, unit {fault.unit})"
        )
        self.fault = fault


class FaultInjector:
    """Seed-driven deterministic fault schedule.

    - ``rates``: ``{point: {kind: probability}}`` (or ``{point: prob}``,
      which takes the point's first legal kind). Each ``draw(point, tick,
      unit)`` maps ``(seed, point, tick, unit)`` through a counter-based
      hash to one uniform — no draw ever observes another draw, so the
      schedule is independent of call order and replays exactly.
    - ``schedule``: explicit one-shot faults —
      ``{"point", "kind", "tick"?, "unit"?, "count"?}``. Omitted ``tick`` /
      ``unit`` match the first opportunity; ``count`` (default 1) fires the
      entry that many times. The smoke uses this for "one dead prefill
      lane".
    - ``delay_ticks``: how many ticks a ``delay`` fault defers a handoff's
      background insert.
    - ``slow_step_s``: seconds a rate-driven ``slow_step`` fault sleeps
      (schedule entries override per-fault via ``{"seconds": ...}``).

    Schedule entries may carry pass-through fields beyond the matchers —
    ``seconds`` (slow_step) and ``exit_code`` (dead_host) ride on
    :attr:`Fault.extra`.

    ``injected`` logs every fault actually drawn, in draw order — two runs
    with the same seed, config, and trace produce identical logs (pinned by
    tests/test_chaos.py, ``make chaos-smoke`` and ``make chaos-train-smoke``).
    """

    def __init__(self, seed: int = 0, rates: Optional[dict] = None,
                 schedule: Optional[list] = None, delay_ticks: int = 3,
                 slow_step_s: float = 0.1):
        self.seed = int(seed)
        self.delay_ticks = int(delay_ticks)
        if self.delay_ticks < 1:
            raise ValueError(f"delay_ticks must be >= 1, got {delay_ticks}")
        self.slow_step_s = float(slow_step_s)
        if self.slow_step_s < 0:
            raise ValueError(f"slow_step_s must be >= 0, got {slow_step_s}")
        self.rates: dict[str, dict[str, float]] = {}
        for point, spec in (rates or {}).items():
            if point not in INJECTION_POINTS:
                raise ValueError(
                    f"unknown injection point {point!r}; known: "
                    f"{INJECTION_POINTS}"
                )
            legal = _POINT_KINDS[point]
            if not isinstance(spec, dict):
                spec = {legal[0]: float(spec)}
            for kind, prob in spec.items():
                if kind not in legal:
                    raise ValueError(
                        f"fault kind {kind!r} is not injectable at {point!r}; "
                        f"legal: {legal}"
                    )
                if not 0.0 <= float(prob) <= 1.0:
                    raise ValueError(
                        f"probability for {point}/{kind} must be in [0, 1], "
                        f"got {prob}"
                    )
            total = sum(float(p) for p in spec.values())
            if total > 1.0:
                raise ValueError(
                    f"probabilities at {point!r} sum to {total} > 1"
                )
            self.rates[point] = {k: float(v) for k, v in spec.items()}
        self._schedule: list[dict] = []
        for entry in (schedule or []):
            e = dict(entry)
            point, kind = e.get("point"), e.get("kind")
            if point not in INJECTION_POINTS:
                raise ValueError(f"schedule entry has unknown point {point!r}")
            if kind not in _POINT_KINDS[point]:
                raise ValueError(
                    f"schedule entry {kind!r} not injectable at {point!r}; "
                    f"legal: {_POINT_KINDS[point]}"
                )
            e.setdefault("count", 1)
            # Anything beyond the matcher keys rides on Fault.extra (e.g.
            # seconds= for slow_step, exit_code= for dead_host).
            e["extra"] = {
                k: v for k, v in e.items()
                if k not in ("point", "kind", "tick", "unit", "count", "extra")
            } or None
            self._schedule.append(e)
        self.injected: list[dict] = []
        # Optional annotation callback (tracing.py attaches here): called
        # with each injected fault's log record so the trace can mark the
        # span the fault hit. Never allowed to break an injection site.
        self.on_inject: Optional[Callable[[dict], None]] = None

    # -- the draw ----------------------------------------------------------

    def draw(self, point: str, tick: int, unit: int = 0) -> Optional[Fault]:
        """One fault decision at ``point`` on scheduler ``tick`` for ``unit``
        (a lane index / request id — disambiguates multiple same-point draws
        within one tick). Returns the :class:`Fault` or None."""
        tick, unit = int(tick), int(unit)
        u = _u01(self.seed, point, tick, unit)
        # Explicit schedule first: the one-shot faults a test pins exactly.
        for entry in self._schedule:
            if entry["count"] <= 0 or entry["point"] != point:
                continue
            if entry.get("tick") is not None and int(entry["tick"]) != tick:
                continue
            if entry.get("unit") is not None and int(entry["unit"]) != unit:
                continue
            entry["count"] -= 1
            return self._log(
                Fault(point, entry["kind"], tick, unit, u, entry["extra"])
            )
        # Rate-driven: walk the point's kinds in declaration order against
        # the single uniform — cumulative, so at most one kind fires.
        acc = 0.0
        for kind, prob in self.rates.get(point, {}).items():
            acc += prob
            if u < acc:
                return self._log(Fault(point, kind, tick, unit, u))
        return None

    def _log(self, fault: Fault) -> Fault:
        rec = {
            "tick": fault.tick, "point": fault.point, "kind": fault.kind,
            "unit": fault.unit,
        }
        self.injected.append(rec)
        if self.on_inject is not None:
            try:
                self.on_inject(rec)
            except Exception:
                logger.exception("chaos on_inject callback failed")
        return fault

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        """Counts by (point, kind) plus the full ordered log length — the
        chaos side of the telemetry ``faults`` block."""
        by: dict[str, int] = {}
        for f in self.injected:
            key = f"{f['point']}:{f['kind']}"
            by[key] = by.get(key, 0) + 1
        return {"injected": len(self.injected), "by_site": dict(sorted(by.items()))}
