"""Disaggregated serving (layer L7 — inference serving, two meshes).

The colocated :class:`~accelerate_tpu.serving.ServingEngine` already gets
slot-paged KV, chunked prefill, and a zero-recompile decode step — but
prefill and decode still share one device queue, so a long prompt burst
stalls every in-flight decode and p95 TTFT spikes under open-loop load.
This module is the DistServe/Splitwise-class fix, planner-shaped: partition
the device set into a **prefill mesh** and a **decode mesh**, sized by
:func:`~accelerate_tpu.planner.plan_disagg_slices` from the prefill:decode
FLOP ratio, and stream each committed KV page across as a device-to-device
transfer the moment its chunk lands.

Architecture (MPMD one level up from arXiv:2412.14374's pipeline stages —
two heterogeneous programs on disjoint device groups, a typed data plane
between them):

- **Prefill lanes** — each lane owns a private ``(L, 1, T_max, Hkv, D)``
  slot cache pinned to one prefill device (round-robin over the slice) and
  runs the SAME jitted prefill program as the colocated engine on it.
  Identical program + identical inputs ⇒ the lane's KV values are
  bit-equal to what an in-place prefill would have written.
- **Streamed KV-page handoff** — after each chunk the lane's freshly
  written page is sliced out and shipped to the decode placement with an
  async ``jax.device_put``; the insert into the decode-side slot cache is
  deferred behind a depth-``handoff_depth`` queue (the double buffer), so
  a page's transfer overlaps the lane's NEXT chunk. The final chunk
  flushes the queue and arms the slot, so decode never observes a
  half-streamed prompt.
- **Two-mesh router** — ``_admit`` grants a request a decode slot AND a
  prefill lane; ``tick()`` advances every lane one chunk (lanes run
  concurrently on their own devices) and then runs the unmodified decode
  step on the decode mesh. The decode program, its donation pattern, and
  its one-executable steady state are untouched — the router only changes
  WHERE cache pages come from, never what they contain.

Bit-equality with the single-mesh engine (pinned by tests/test_disagg.py):
pages are copied pad-tail and all, attention is bounded at each row's true
length, and every request samples from its own PRNG stream — so neither
the transfer nor the two-mesh tick interleaving can change any token.

CPU tier-1 story: force a multi-device host platform
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) and the same code
splits the 8 "devices" into disjoint slices — the transfers are real
cross-device copies, just over host memory.

Usage::

    from accelerate_tpu import DisaggConfig, DisaggServingEngine

    engine = DisaggServingEngine(
        model, ServingConfig(n_slots=8, eos_token_id=2),
        disagg=DisaggConfig(n_prefill_lanes=2),
    )
    outs = engine.run(prompts, max_new_tokens=64)   # same API, same tokens
    engine.stats()["disagg"]                        # slices + handoff costs
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P, SingleDeviceSharding

from .chaos import InjectedFaultError, deterministic_jitter
from .generation import KVCache, QuantPages, init_slot_cache
from .logging import get_logger
from .planner import (BandwidthTable, PlannerError, kv_bytes_per_token,
                      plan_disagg_slices)
from .resharding import ReshardExecutor
from .serving import (ServingEngine, SlotState, _cache_size, _release_step,
                      init_slot_state, plan_chunks)

logger = get_logger(__name__)


def _log_ok() -> bool:
    """The repo logger needs accelerate state; the engine must also work
    standalone (no Accelerator), where init-time logs are just skipped."""
    from .state import PartialState

    return bool(PartialState._shared_state)


@dataclass
class _Lane:
    """One prefill workspace: a single-slot cache + state pinned to one
    prefill device. A lane prefills one request at a time; ``cache`` and
    ``state`` are rebound to the jitted program's (donated) outputs every
    chunk, so the arrays live on ``device`` for the lane's lifetime."""

    index: int
    device: Any
    params: Any
    cache: KVCache
    state: SlotState


@dataclass
class _Handoff:
    """One committed KV page in flight to the decode mesh."""

    slot: int
    start: int            # write offset in the decode-side cache
    valid: int            # real prompt tokens in the page (rest is pad tail)
    pages: tuple          # (k_page, v_page) already device_put to decode
    nbytes: int
    arm: Optional[tuple] = None   # (tok, done0, rng_carry) on the final chunk
    budget: int = 0
    t0: Optional[float] = None    # perf_counter at dispatch when sampled
    ready_tick: int = 0   # straggler model: background drains wait for this
                          # tick; forced drains (depth overflow, final flush)
                          # await the transfer and proceed
    rid: int = -1                 # owning request (trace span tree key)
    trace_flow: Optional[int] = None  # Chrome-trace flow id: stitches this
                                      # page's lane-side dispatch to its
                                      # decode-slot insert


@dataclass
class _DrainingLayout:
    """A retired topology still finishing its in-flight decodes after a live
    resize. The old decode cache/state and every param version it might
    reference stay bound here (and ONLY here) until ``decoding`` empties —
    then the whole layout drops and its buffers go with it. Draining slots
    index THIS layout's state, never the active free list."""

    layout_id: int
    cache: KVCache
    state: SlotState
    params_by_version: dict
    decoding: dict          # slot -> request, frozen membership, drains down
    trace_span: Optional[int] = None  # open "drain" span handle (tracing.py)


class DisaggServingEngine(ServingEngine):
    """Two-mesh router over the continuous-batching engine: chunked prefill
    on a planner-sized prefill slice, the zero-recompile decode step on the
    complementary decode slice, committed KV pages streamed between them.

    Same front-end API as :class:`~accelerate_tpu.serving.ServingEngine`
    (``submit/tick/poll/run``) and token-for-token the same outputs; the
    extra ``disagg`` kwarg (a :class:`~accelerate_tpu.utils.DisaggConfig`)
    and the ``devices`` override (default: ``jax.devices()``) control the
    split. ``stats()`` gains a ``"disagg"`` block: the slice plan, handoff
    bytes/latency, and measured FLOP ratio for re-planning.
    """

    def __init__(self, model, config=None, *, disagg=None, devices=None,
                 forward_cached=None, compile_manager=None, telemetry=None,
                 fault_tolerance=None, chaos=None, tracing=None, journal=None,
                 profiler=None):
        from .utils.dataclasses import DisaggConfig

        self.disagg_config = disagg if disagg is not None else DisaggConfig()
        devs = list(devices) if devices is not None else list(jax.devices())
        if len(devs) < 2:
            raise ValueError(
                f"disaggregation needs >= 2 devices to split into a prefill "
                f"and a decode mesh, got {len(devs)}; on CPU force a "
                "multi-device host platform with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N"
            )
        super().__init__(model, config, forward_cached=forward_cached,
                         compile_manager=compile_manager, telemetry=telemetry,
                         fault_tolerance=fault_tolerance, chaos=chaos,
                         tracing=tracing, journal=journal, profiler=profiler)
        dc = self.disagg_config
        # Degradation state: quarantined lanes leave the pool for good; once
        # EVERY lane is gone the engine latches degraded and prefills
        # colocated on the decode mesh (correct, slower — traffic survives).
        self._quarantined_lanes: set[int] = set()
        self._degraded = False
        # Live-resize state (autoscale.py drives this): the ordered device
        # set the engine currently runs on, retired layouts still draining
        # their in-flight decodes, and the resize telemetry counters.
        self._devices = devs
        self._resize_seq = 0
        self._draining_layouts: list[_DrainingLayout] = []
        self._rstats = {
            "resizes": 0, "resize_aborts": 0, "resize_retries": 0,
            "resize_delays": 0, "drained_layouts": 0, "rebound_requests": 0,
            "retried_decodes": 0, "moved_bytes": 0, "transfer_wall_s": 0.0,
        }

        # -- slice sizing (planner cost model) -----------------------------
        ratio = dc.prefill_decode_flop_ratio
        if ratio is None:
            expected = (dc.expected_prompt_tokens
                        if dc.expected_prompt_tokens is not None
                        else max(1.0, self.t_max / 2.0))
            ratio = expected / max(1, int(self.config.max_new_tokens))
        kvb = kv_bytes_per_token(self.cfg, dtype=self._cache.k.dtype)
        self.slice_plan = plan_disagg_slices(
            len(devs), prefill_decode_flop_ratio=ratio,
            bw=BandwidthTable.from_dict(dc.bandwidths),
            kv_bytes_per_token=kvb, n_prefill=dc.n_prefill_devices,
        )
        # The decode slice is also what the SDC decode canary (sdc.py)
        # convicts on a bit-wise output mismatch: decode_devices[0] is the
        # quarantine target handed to the autoscaler's mark_device_dead.
        self.prefill_devices = devs[:self.slice_plan.n_prefill]
        self.decode_devices = devs[self.slice_plan.n_prefill:]

        # -- decode mesh ---------------------------------------------------
        # jit caches one executable PER PLACEMENT, so the one-executable
        # decode invariant requires a FIXED decode placement. Default: the
        # decode slice's first device hosts the slot cache (the census then
        # reads exactly 1). Opt-in (shard_decode_slots): slots sharded over
        # the decode slice — same single compiled program, but typed
        # PRNG-key arrays under a multi-device NamedSharding occupy two
        # dispatch-cache entries per program in jax 0.4.37, so init
        # pre-warms both and the census reads a flat 2.
        (self._decode_mesh, cache_s, vec_s,
         self._decode_sharding) = self._decode_placement(self.decode_devices)
        self._cache = jax.device_put(
            self._cache, KVCache(cache_s, cache_s, vec_s))
        self._state = jax.device_put(
            self._state, SlotState(*([vec_s] * len(SlotState._fields))))
        self._params_decode = jax.device_put(model.params, self._decode_sharding)
        self._params = self._params_decode  # what the decode hook dispatches
        # Version 0's buffers are the decode-mesh copy, not the model's own
        # placement — keep the publication double-buffer consistent with
        # what the dispatch hooks actually feed the programs.
        self._params_by_version[0] = self._params_decode

        # -- prefill lanes -------------------------------------------------
        params_by_dev: dict = {}
        self._lanes: list[_Lane] = []
        for i in range(int(dc.n_prefill_lanes)):
            dev = self.prefill_devices[i % len(self.prefill_devices)]
            if dev not in params_by_dev:
                params_by_dev[dev] = jax.device_put(model.params, dev)
            self._lanes.append(_Lane(
                index=i, device=dev, params=params_by_dev[dev],
                cache=jax.device_put(
                    init_slot_cache(self.cfg, 1, self.t_max,
                                    dtype=self.config.cache_dtype), dev),
                state=jax.device_put(
                    init_slot_state(1, seed=self.config.seed,
                                    history=self._spec_ngram), dev),
            ))
        # FIFO lane reuse: grants take the least-recently-freed lane, so a
        # request wave strides across every lane (and warmup covers each
        # lane's device with every ladder rung).
        self._free_lanes: deque[_Lane] = deque(self._lanes)
        # Published versions carry per-prefill-device param copies too (one
        # per unique lane device, like construction): version -> dev -> tree.
        self._lane_params: dict[int, dict] = {0: params_by_dev}

        # -- the data plane ------------------------------------------------
        self._handoffs: deque[_Handoff] = deque()
        self._handoff_lat_s: list[float] = []
        self._hstats = {"transfers": 0, "bytes": 0, "inserts": 0,
                        "flushes": 0, "lane_chunks": 0}

        # Page extract: slice the lane's freshly written page out of its
        # (L, 1, T_max, Hkv, D) cache. One executable per ladder rung.
        # Tree-mapped so int8 QuantPages (data + per-page scale leaves,
        # both T-major on axis 2) slice as one unit.
        self._extract = jax.jit(
            lambda k, v, start, size: jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, start, size, axis=2),
                (k, v),
            ),
            static_argnums=(3,),
        )

        # Page insert: write a transferred page into the decode-side slot
        # cache at the request's own offset, and commit its true length.
        def _insert(cache: KVCache, k_page, v_page, slot, start, valid):
            zero = jnp.zeros((), jnp.int32)

            def upd(a, page):
                return jax.lax.dynamic_update_slice(
                    a, page, (zero, slot, start, zero, zero))

            k = jax.tree.map(upd, cache.k, k_page)
            v = jax.tree.map(upd, cache.v, v_page)
            return KVCache(k, v, cache.length.at[slot].set(start + valid))

        self._insert = jax.jit(_insert, donate_argnums=(0,))

        # Slot arming: once the final page has landed, publish the prefill
        # step's terminal state for this slot — exactly the fields the
        # colocated prefill's final chunk writes (garbage written by
        # intermediate chunks is unobservable there too: active stays
        # False until this moment).
        def _arm(state: SlotState, slot, tok, done0, budget, carry, hist):
            return SlotState(
                last_token=state.last_token.at[slot].set(tok),
                active=state.active.at[slot].set(True),
                done=state.done.at[slot].set(done0),
                generated=state.generated.at[slot].set(1),
                budget=state.budget.at[slot].set(budget),
                rng=state.rng.at[slot].set(carry),
                history=state.history.at[slot].set(hist),
            )

        self._arm = jax.jit(_arm, donate_argnums=(0,))

        if self._decode_mesh is not None:
            # Pre-warm BOTH dispatch-cache entries the typed-key NamedSharding
            # path occupies (one compiled program either way — see the
            # shard_decode_slots note in DisaggConfig), so the steady-state
            # census is flat from the first real tick. Safe for bit-equality:
            # every slot is inactive, garbage KV lands below future inserts
            # and past true lengths (never attended), and idle slots' rng
            # streams are dead until _arm rewrites them.
            for _ in range(4):
                # No live rows: lengths pass through unchanged, k/v garbage
                # lands where inserts overwrite or attention never reaches.
                self._cache, self._state, _, _, _ = self._decode(
                    self._params, self._cache, self._state, self._full_mask)

        if _log_ok():
            logger.info(
                "disagg: %d devices -> %d prefill / %d decode (ratio %.3g, "
                "bottleneck %s, predicted speedup %.3gx), %d lane(s), "
                "handoff %.3g GB/s",
                self.slice_plan.n_devices, self.slice_plan.n_prefill,
                self.slice_plan.n_decode, self.slice_plan.flop_ratio,
                self.slice_plan.bottleneck, self.slice_plan.predicted_speedup,
                len(self._lanes), self.slice_plan.handoff_gbps,
            )

    def _decode_placement(self, decode_devices) -> tuple:
        """``(mesh, cache_sharding, vec_sharding, scalar_sharding)`` for a
        decode slice — shared by construction and the live resize so both
        layouts obey the same one-executable placement rules."""
        dc = self.disagg_config
        n_d = len(decode_devices)
        if dc.shard_decode_slots and n_d > 1 and self.n_slots % n_d == 0:
            mesh = Mesh(np.asarray(decode_devices), ("slots",))
            return (mesh, NamedSharding(mesh, P(None, "slots")),
                    NamedSharding(mesh, P("slots")), NamedSharding(mesh, P()))
        if dc.shard_decode_slots and _log_ok():
            logger.warning_once(
                "disagg: shard_decode_slots needs n_slots (%d) divisible "
                "by the decode slice (%d devices); falling back to "
                "single-device decode placement.", self.n_slots, n_d,
            )
        single = SingleDeviceSharding(decode_devices[0])
        return None, single, single, single

    # -- router scheduling -------------------------------------------------

    def tick(self) -> None:
        """One router round: sweep deadlines/preemption, admit into free
        slots (same policy as the colocated engine — lanes never gate
        admission, only prefill concurrency), drain pages whose transfer had
        a full tick to fly, advance EVERY lane-holding request one chunk
        (disjoint devices — the chunks run concurrently), then one decode
        step on the decode mesh. Degraded mode (every lane quarantined)
        prefills head-of-line colocated on the decode mesh instead."""
        prof = self._profiler
        t0 = time.perf_counter() if prof is not None else 0.0
        tick_no = self._stats["ticks"]
        snap = self._begin_tick()
        self._admit()
        self._sample_queue_depth()
        self._drain_handoffs()
        if not self._degraded:
            self._assign_lanes()
        t1 = time.perf_counter() if prof is not None else 0.0
        for _ in range(max(1, int(self.config.prefill_chunks_per_tick))):
            if self._degraded:
                # Colocated fallback: the base head-of-line discipline, the
                # base dispatch path (lane is None routes there).
                if not self._prefilling:
                    break
                self._prefill_one(self._prefilling[0])
            else:
                runnable = [r for r in self._prefilling if r.lane is not None]
                if not runnable:
                    break
                for req in runnable:
                    self._prefill_one(req)
        t2 = time.perf_counter() if prof is not None else 0.0
        self._tick_fetch_s = 0.0  # filled by _decode_tick's device_get timer
        if self._decoding:
            self._decode_tick()
        self._drain_decode_tick()
        t3 = time.perf_counter() if prof is not None else 0.0
        self._end_tick(snap)
        if prof is not None:
            # Same lagged per-tick attribution as the colocated engine's
            # tick (serving.py): host perf_counter sections only, the
            # bookkeeping residual closes the identity. admit_s absorbs the
            # router-only phases (handoff drain + lane assignment).
            t4 = time.perf_counter()
            prof.on_tick(
                tick_no, t4 - t0,
                sections={
                    "admit_s": t1 - t0,
                    "prefill_s": t2 - t1,
                    "decode_s": (t3 - t2) - self._tick_fetch_s,
                    "host_fetch_s": self._tick_fetch_s,
                    "bookkeeping_s": t4 - t3,
                },
                gauges={
                    "journal_lsn": (self._journal.stats()["appends"]
                                    if self._journal is not None else None),
                    "jit_cache": self.executable_counts(),
                    "occupancy": len(self._decoding),
                },
            )

    def _assign_lanes(self) -> None:
        """Hand free lanes to lane-less prefilling requests, health-checking
        each lane at grant time (the ``lane_health`` injection point — a
        dead lane is quarantined before it ever touches a request)."""
        for req in list(self._prefilling):
            if req.lane is not None:
                continue
            while self._free_lanes and req.lane is None:
                lane = self._free_lanes.popleft()
                if self.chaos is not None:
                    fault = self.chaos.draw("lane_health",
                                            self._stats["ticks"],
                                            unit=lane.index)
                    if fault is not None and fault.kind == "dead_lane":
                        self._quarantine_lane(lane, "failed health check")
                        continue
                req.lane = lane
            if req.lane is None:  # no healthy free lane left this tick
                break

    # -- prefill mesh + handoff --------------------------------------------

    def _prefill_dispatch(self, req, chunk, valid: int,
                          is_first: bool, is_final: bool):
        """Run the chunk on the request's lane (prefill mesh), then stream
        the committed page to the decode placement. The device_put is
        async: the copy overlaps the lane's next chunk, and the insert is
        deferred behind the handoff queue until it has had time to land.

        A lane-less request (degraded mode — every lane quarantined) routes
        to the base colocated dispatch: same prefill program on the decode
        placement, writing the decode-side cache directly. No handoff, no
        arm; the decode step and its ONE executable never notice."""
        if req.lane is None:
            return super()._prefill_dispatch(req, chunk, valid, is_first,
                                             is_final)
        lane = req.lane
        dc = self.disagg_config
        start = req.consumed  # host-tracked — lane slot 0 IS this request
        lane.cache, lane.state, tok, done0 = self._prefill(
            self._lane_params[req.weights_version][lane.device],
            lane.cache, lane.state, chunk,
            np.int32(0), np.int32(valid), np.int32(req.budget),
            req.rng, is_first, is_final,
        )
        self._hstats["lane_chunks"] += 1

        size = int(chunk.shape[1])
        pages = self._extract(lane.cache.k, lane.cache.v, np.int32(start), size)
        self._hstats["transfers"] += 1
        t0 = None
        if self._hstats["transfers"] % dc.handoff_sample_every == 0:
            # Sampled end-to-end handoff timing: settle the source page so
            # the clock starts at transfer dispatch, not at lane compute.
            jax.block_until_ready(pages)
            t0 = time.perf_counter()
        tr = self.tracing
        th0 = time.perf_counter() if tr is not None else None
        pages_d, delay_ticks = self._handoff_put(req, lane, pages)
        nbytes = int(pages[0].nbytes + pages[1].nbytes)
        self._hstats["bytes"] += nbytes

        arm = None
        if is_final:
            # The decode-side slot inherits the lane's terminal per-request
            # state: first token, done flag, and the rng carry the final
            # prefill chunk advanced to — decode then continues the SAME
            # per-request stream the colocated engine would.
            arm = jax.device_put(
                (tok, done0, lane.state.rng[0], lane.state.history[0]),
                self._decode_sharding)
        self._handoffs.append(_Handoff(
            slot=req.slot, start=start, valid=int(valid), pages=pages_d,
            nbytes=nbytes, arm=arm, budget=int(req.budget), t0=t0,
            ready_tick=self._stats["ticks"] + delay_ticks, rid=req.id,
        ))
        if tr is not None:
            # Flow id stitches this page's lane-side span to the decode-slot
            # insert in the Chrome export; set before any forced drain below
            # can pop the handoff back off.
            self._handoffs[-1].trace_flow = tr.handoff(
                req.id, self._stats["ticks"], th0, time.perf_counter(),
                lane=lane.index, slot=req.slot, nbytes=nbytes, final=is_final)
        if is_final:
            # Flush before decode can observe the slot, and release the
            # lane — its buffers are donated to the next occupant's first
            # chunk (XLA keeps pending readers safe).
            tf0 = time.perf_counter() if tr is not None else None
            self._drain_handoffs(drain_all=True)
            if tr is not None:
                tr.handoff_flush(req.id, self._stats["ticks"], tf0,
                                 time.perf_counter())
            self._hstats["flushes"] += 1
            self._free_lanes.append(lane)
            req.lane = None
        else:
            while len(self._handoffs) > dc.handoff_depth:
                self._drain_one()
        return tok, done0

    def _handoff_put(self, req, lane: _Lane, pages) -> tuple:
        """The guarded transfer: one chaos draw at ``handoff_device_put``,
        then the device_put with up to ``handoff_retries`` capped
        jitter-backoff retries. A transient injected transfer error
        (``fault.u < 0.75``) fails exactly one attempt; a persistent one (or
        a real failure that survives every retry) quarantines the lane and
        re-raises — the base recovery path then re-queues the request for
        an idempotent re-prefill. Returns ``(pages_on_decode,
        delay_ticks)`` where ``delay_ticks`` models a straggler transfer."""
        dc = self.disagg_config
        fault = None
        if self.chaos is not None:
            fault = self.chaos.draw("handoff_device_put",
                                    self._stats["ticks"], unit=req.id)
        delay_ticks = 0
        if fault is not None and fault.kind == "delay":
            self._fstats["handoff_delays"] += 1
            delay_ticks = int(self.chaos.delay_ticks)
            fault = None
        poison = fault is not None and fault.kind == "poison"
        attempts = int(dc.handoff_retries) + 1
        for attempt in range(attempts):
            try:
                if (fault is not None and fault.kind == "transfer_error"
                        and (attempt == 0 or fault.u >= 0.75)):
                    raise InjectedFaultError(fault)
                pages_d = jax.device_put(pages, self._decode_sharding)
                break
            except RuntimeError as e:
                if attempt == attempts - 1:
                    self._quarantine_lane(
                        lane, f"handoff failed {attempts}x: {e}")
                    raise
                self._fstats["handoff_retries"] += 1
                backoff = min(
                    float(dc.handoff_backoff_s) * (2 ** attempt),
                    float(dc.handoff_backoff_cap_s),
                ) * deterministic_jitter(
                    self.chaos.seed if self.chaos is not None else 0,
                    self._stats["ticks"], attempt,
                )
                if self.tracing is not None:
                    tb0 = time.perf_counter()
                    if backoff > 0:
                        time.sleep(backoff)
                    # The measured sleep wall (not the computed value) feeds
                    # explain()'s backoff term so it telescopes exactly.
                    self.tracing.handoff_retry(
                        req.id, self._stats["ticks"], attempt=attempt,
                        backoff_s=backoff, lane=lane.index,
                        measured_s=time.perf_counter() - tb0)
                elif backoff > 0:
                    time.sleep(backoff)
        if poison and jnp.issubdtype(pages[0].dtype, jnp.floating):
            # Poisoned page: what lands on the decode mesh is all-NaN. The
            # decode-side nonfinite-logits sentinel must catch it once the
            # slot arms — pinned by tests and the chaos smoke.
            pages_d = jax.device_put(
                (jnp.full_like(pages[0], jnp.nan),
                 jnp.full_like(pages[1], jnp.nan)),
                self._decode_sharding,
            )
        if isinstance(pages[0], QuantPages) and self.chaos is not None:
            dq = self.chaos.draw("page_dequant", self._stats["ticks"],
                                 unit=req.id)
            if dq is not None and dq.kind == "poison":
                # Quantized twin of the float poison: int8 payloads are
                # always finite, so corrupt the dequant SCALES — attention's
                # in-kernel dequantize then propagates NaN and the same
                # nonfinite-logits sentinel convicts the slot.
                pages_d = jax.device_put(
                    tuple(QuantPages(p.data, jnp.full_like(p.scale, jnp.nan))
                          for p in pages),
                    self._decode_sharding,
                )
        return pages_d, delay_ticks

    def _drain_handoffs(self, drain_all: bool = False) -> None:
        if drain_all:
            while self._handoffs:
                self._drain_one()
        else:
            # Pages queued on earlier ticks have had >= 1 tick of transfer
            # time; keep at most the configured double buffer in flight. A
            # straggler head (ready_tick in the future) blocks background
            # draining — FIFO order is what keeps per-slot lengths
            # monotone — until a forced drain awaits it.
            while (len(self._handoffs) > self.disagg_config.handoff_depth
                   and self._handoffs[0].ready_tick <= self._stats["ticks"]):
                self._drain_one()

    def _purge_slot(self, slot: int) -> None:
        """Drop every in-flight handoff targeting ``slot`` (its request was
        evicted or is being retried) so a stale page can never land in the
        slot's next grant."""
        keep = deque(h for h in self._handoffs if h.slot != slot)
        dropped = len(self._handoffs) - len(keep)
        if dropped:
            self._handoffs = keep
            if _log_ok():
                logger.warning(
                    "disagg: purged %d in-flight handoff page(s) for slot %d",
                    dropped, slot,
                )

    def _release_lane(self, req, failed: bool = False) -> None:
        """Return the request's lane to the free pool — unless it was
        quarantined by the failure that got us here, in which case it stays
        out of rotation."""
        lane, req.lane = req.lane, None
        if lane is None or lane.index in self._quarantined_lanes:
            return
        self._free_lanes.append(lane)

    def _quarantine_lane(self, lane: _Lane, reason: str) -> None:
        if lane.index in self._quarantined_lanes:
            return
        self._quarantined_lanes.add(lane.index)
        self._fstats["lane_quarantines"] += 1
        if self.tracing is not None:
            self.tracing.quarantine("lane", lane.index, self._stats["ticks"],
                                    reason=reason)
        try:
            self._free_lanes.remove(lane)
        except ValueError:
            pass  # held by a request; _release_lane won't re-pool it
        healthy = len(self._lanes) - len(self._quarantined_lanes)
        if _log_ok():
            logger.warning(
                "disagg: quarantined prefill lane %d on %s (%s); %d/%d "
                "lane(s) remain", lane.index, lane.device, reason, healthy,
                len(self._lanes),
            )
        if self.telemetry is not None:
            self.telemetry.record_event(
                "serving_lane_quarantined", lane=lane.index, reason=reason,
            )
        if healthy == 0 and not self._degraded:
            self._degraded = True
            if _log_ok():
                logger.warning_once(
                    "disagg: every prefill lane is quarantined — degrading "
                    "to colocated prefill on the decode mesh (correct but "
                    "slower; p95 TTFT will rise). Restart the engine to "
                    "restore the prefill slice."
                )
            if self.telemetry is not None:
                self.telemetry.record_event("serving_degraded")

    def _drain_one(self) -> None:
        h = self._handoffs.popleft()
        k_page, v_page = h.pages
        self._cache = self._insert(
            self._cache, k_page, v_page,
            np.int32(h.slot), np.int32(h.start), np.int32(h.valid),
        )
        self._hstats["inserts"] += 1
        if h.arm is not None:
            tok, done0, carry, hist = h.arm
            self._state = self._arm(
                self._state, np.int32(h.slot), tok, done0,
                np.int32(h.budget), carry, hist,
            )
        if h.t0 is not None:
            jax.block_until_ready(k_page)
            self._handoff_lat_s.append(time.perf_counter() - h.t0)
        if self.tracing is not None:
            self.tracing.handoff_insert(
                self._stats["ticks"], slot=h.slot, flow=h.trace_flow,
                request_id=(h.rid if h.rid >= 0 else None),
                armed=h.arm is not None)

    # -- live resize (the autoscale.py actuator) ---------------------------

    def resize(self, devices=None, *, n_prefill=None, flop_ratio=None,
               dead_devices=()) -> dict:
        """Live re-split / grow / shrink with zero downtime: build the whole
        target layout (plan, decode placement, param copies for EVERY
        installed version, lanes, pre-warmed executables) BEFORE touching
        live state, then commit in one host-side swap. In-flight decodes
        keep draining on the old layout (:class:`_DrainingLayout`);
        mid-prefill requests re-queue at the head WITHOUT spending a retry
        (their per-request rng replays bit-equal); new admissions bind the
        new layout. A failure anywhere before the commit — planner refusal,
        an injected/real ``resize_transfer`` error surviving the
        ``handoff_retries`` budget — aborts with the old layout untouched
        and nothing half-bound.

        ``devices`` defaults to the current set minus ``dead_devices``;
        ``flop_ratio`` (the observed prompt:decode ratio) re-runs the
        planner split; ``n_prefill`` pins it. Returns a record dict
        (``{"ok": bool, ...}``) that also lands in telemetry."""
        dc = self.disagg_config
        dead = set(dead_devices)
        devs = (list(devices) if devices is not None
                else [d for d in self._devices if d not in dead])
        seq = self._resize_seq
        self._resize_seq += 1
        old_n = len(self._devices)
        tr = self.tracing
        h_resize = (tr.begin("resize", f"resize[{seq}]", self._stats["ticks"],
                             seq=seq, old_devices=old_n,
                             new_devices=len(devs))
                    if tr is not None else None)

        def abort(reason: str) -> dict:
            self._rstats["resize_aborts"] += 1
            if tr is not None:
                # Ending the outer span force-closes whichever phase span
                # (plan/build) was open when the failure hit.
                tr.end(h_resize, self._stats["ticks"], ok=False,
                       reason=reason)
            if _log_ok():
                logger.warning(
                    "disagg: resize %d -> %d devices ABORTED (%s) — old "
                    "layout keeps serving", old_n, len(devs), reason,
                )
            rec = {"ok": False, "seq": seq, "reason": reason,
                   "n_devices": len(devs), "layout_id": self._active_layout_id}
            if self.telemetry is not None:
                try:
                    self.telemetry.record_event(
                        "serving_resize_aborted", seq=seq, reason=reason,
                        n_devices=len(devs))
                except Exception:
                    pass
            return rec

        # -- validate + plan (nothing live touched yet) --------------------
        h_plan = (tr.begin("resize", "plan", self._stats["ticks"])
                  if tr is not None else None)
        if any(d in dead for d in devs):
            return abort("target includes a dead device")
        if len(devs) < 2:
            return abort(f"needs >= 2 devices, got {len(devs)}")
        ratio = (float(flop_ratio) if flop_ratio is not None
                 else float(self.slice_plan.flop_ratio))
        try:
            kvb = kv_bytes_per_token(self.cfg, dtype=self._cache.k.dtype)
            plan = plan_disagg_slices(
                len(devs), prefill_decode_flop_ratio=ratio,
                bw=BandwidthTable.from_dict(dc.bandwidths),
                kv_bytes_per_token=kvb, n_prefill=n_prefill,
            )
        except PlannerError as e:
            return abort(f"planner refused: {e}")

        new_prefill = devs[:plan.n_prefill]
        new_decode = devs[plan.n_prefill:]
        mesh, cache_s, vec_s, dsh = self._decode_placement(new_decode)
        if tr is not None:
            tr.end(h_plan, self._stats["ticks"], n_prefill=plan.n_prefill,
                   n_decode=plan.n_decode)
            h_build = tr.begin("resize", "build", self._stats["ticks"])

        # -- param redistribution across the topology gap ------------------
        # The reshard executor prices and batches the copies; donate=False
        # keeps the OLD layout's buffers alive for its draining requests.
        # One chaos draw per resize at ``resize_transfer`` (tick = seq), the
        # same transient-vs-persistent retry convention as the handoff path.
        fault = None
        if self.chaos is not None:
            fault = self.chaos.draw("resize_transfer", seq, unit=0)
        if fault is not None and fault.kind == "delay":
            self._rstats["resize_delays"] += 1
            time.sleep(min(float(dc.handoff_backoff_cap_s),
                           float(dc.handoff_backoff_s)
                           * int(self.chaos.delay_ticks)))
            fault = None
        executor = ReshardExecutor(Mesh(np.asarray(new_decode), ("decode",)))
        t0 = time.perf_counter()
        new_params_by_version = None
        attempts = int(dc.handoff_retries) + 1
        for attempt in range(attempts):
            try:
                if (fault is not None and fault.kind == "transfer_error"
                        and (attempt == 0 or fault.u >= 0.75)):
                    raise InjectedFaultError(fault)
                new_params_by_version = {
                    v: executor.put_tree(
                        p, jax.tree_util.tree_map(lambda _: dsh, p),
                        donate=False)
                    for v, p in self._params_by_version.items()
                }
                break
            except RuntimeError as e:
                if attempt == attempts - 1:
                    return abort(f"param transfer failed {attempts}x: {e}")
                self._rstats["resize_retries"] += 1
                backoff = min(
                    float(dc.handoff_backoff_s) * (2 ** attempt),
                    float(dc.handoff_backoff_cap_s),
                ) * deterministic_jitter(
                    self.chaos.seed if self.chaos is not None else 0,
                    seq, attempt,
                )
                if backoff > 0:
                    time.sleep(backoff)
        ex_stats = executor.stats()
        self._rstats["moved_bytes"] += int(ex_stats["bytes"])
        self._rstats["transfer_wall_s"] += time.perf_counter() - t0

        # -- build the rest of the target layout ---------------------------
        new_cache = jax.device_put(
            init_slot_cache(self.cfg, self.n_slots, self.t_max,
                            dtype=self.config.cache_dtype),
            KVCache(cache_s, cache_s, vec_s))
        new_state = jax.device_put(
            init_slot_state(self.n_slots, seed=self.config.seed,
                            history=self._spec_ngram),
            SlotState(*([vec_s] * len(SlotState._fields))))
        new_lane_params: dict[int, dict] = {}
        for v, p in new_params_by_version.items():
            by_dev: dict = {}
            for i in range(int(dc.n_prefill_lanes)):
                dev = new_prefill[i % len(new_prefill)]
                if dev not in by_dev:
                    by_dev[dev] = jax.device_put(p, dev)
            new_lane_params[v] = by_dev
        primary_lane_params = new_lane_params[self._weights_version]
        new_lanes = [
            _Lane(index=i, device=new_prefill[i % len(new_prefill)],
                  params=primary_lane_params[new_prefill[i % len(new_prefill)]],
                  cache=jax.device_put(
                      init_slot_cache(self.cfg, 1, self.t_max,
                                      dtype=self.config.cache_dtype),
                      new_prefill[i % len(new_prefill)]),
                  state=jax.device_put(
                      init_slot_state(1, seed=self.config.seed,
                                      history=self._spec_ngram),
                      new_prefill[i % len(new_prefill)]))
            for i in range(int(dc.n_prefill_lanes))
        ]
        new_cache, new_state = self._warm_layout(
            new_params_by_version[self._weights_version], new_cache,
            new_state, new_lanes, primary_lane_params, dsh, mesh)
        if tr is not None:
            tr.end(h_build, self._stats["ticks"],
                   moved_bytes=int(ex_stats["bytes"]))
            h_commit = tr.begin("resize", "commit", self._stats["ticks"])

        # -- commit: one host-side swap, nothing half-bound ----------------
        old_decode_dead = any(d in dead for d in self.decode_devices)
        retired = _DrainingLayout(
            layout_id=self._active_layout_id, cache=self._cache,
            state=self._state, params_by_version=self._params_by_version,
            decoding=self._decoding,
        )
        retried = 0
        rebound = 0
        self._decoding = {}
        self._handoffs.clear()  # stale pages target the retired placement
        if retired.decoding:
            if old_decode_dead:
                # The old decode placement lost a device: its KV is gone, so
                # every in-flight decode replays from scratch (idempotent —
                # same prompt/rng/version), spending one retry each.
                for req in list(retired.decoding.values()):
                    req.slot = None
                    retried += 1
                    self._rstats["retried_decodes"] += 1
                    self._retry_or_fail(
                        req, reason="decode device lost in resize")
                retired.decoding = {}
            else:
                self._draining_layouts.append(retired)
                if tr is not None:
                    # Detached: the drain outlives this method, ending in
                    # _prune_drained whenever the last decode finishes.
                    retired.trace_span = tr.begin(
                        "resize", f"drain[layout {retired.layout_id}]",
                        self._stats["ticks"], detached=True,
                        draining=len(retired.decoding))
        # Mid-prefill requests re-queue at the head in their original order,
        # WITHOUT spending a retry — a resize is not a failure. reset binds
        # slot/lane to None; weights_version survives (every installed
        # version was copied), so the replay is bit-equal.
        for req in reversed(list(self._prefilling)):
            req.reset_for_retry()
            rebound += 1
            self._rstats["rebound_requests"] += 1
            self._queue.appendleft(req)
        self._prefilling.clear()
        self._free = list(range(self.n_slots - 1, -1, -1))
        self._used_slots = set()
        self._quarantined_slots = set()
        self._quarantined_lanes = set()
        self._degraded = False
        self._cache, self._state = new_cache, new_state
        self._params_by_version = new_params_by_version
        self._params = new_params_by_version[self._weights_version]
        self._params_decode = self._params
        self._lane_params = new_lane_params
        self._lanes = new_lanes
        self._free_lanes = deque(new_lanes)
        self.slice_plan = plan
        self.prefill_devices = new_prefill
        self.decode_devices = new_decode
        self._decode_mesh = mesh
        self._decode_sharding = dsh
        self._devices = devs
        self._active_layout_id += 1
        # Per-layout executables are a feature, not a recompile: re-baseline
        # the census at the (pre-warmed) post-commit size, so "steady
        # recompiles" keeps meaning what it meant — growth WITHIN a layout.
        size = _cache_size(self._decode)
        if size is not None:
            self._decode_executables_baseline = size
        self._rstats["resizes"] += 1
        if tr is not None:
            tr.end(h_commit, self._stats["ticks"], rebound=rebound,
                   retried=retried)
            tr.end(h_resize, self._stats["ticks"], ok=True,
                   layout_id=self._active_layout_id)
        if _log_ok():
            logger.info(
                "disagg: resized %d -> %d devices (%d prefill / %d decode, "
                "ratio %.3g, layout %d); %d request(s) rebound, %d retried, "
                "%d draining", old_n, len(devs), plan.n_prefill,
                plan.n_decode, ratio, self._active_layout_id, rebound,
                retried, len(retired.decoding),
            )
        rec = {
            "ok": True, "seq": seq, "layout_id": self._active_layout_id,
            "n_devices": len(devs), "n_prefill": plan.n_prefill,
            "n_decode": plan.n_decode, "flop_ratio": round(ratio, 6),
            "rebound": rebound, "retried": retried,
            "draining": len(retired.decoding),
            "moved_bytes": int(ex_stats["bytes"]),
        }
        if self.telemetry is not None:
            try:
                self.telemetry.record_event("serving_resized", **rec)
            except Exception:
                pass
        return rec

    def _warm_layout(self, params, cache, state, lanes, lane_params, dsh,
                     mesh) -> tuple:
        """Pre-commit compile warm for a target layout: every ladder rung on
        one lane per unique prefill device (prefill + extract), the per-rung
        inserts and the arm on the new decode placement, then the decode
        step itself. All on the NEW buffers — a failure here aborts the
        resize with live state untouched; after the commit the new layout
        serves its first real request with zero compile pauses. Safe for
        bit-equality for the same reason construction's pre-warm is: the
        garbage lands in inactive rows/below future inserts, and the one
        armed slot is released before anything can observe it."""
        prompt_len = min(sum(self.ladder), self.t_max - 2)
        chunks = plan_chunks(prompt_len, self.ladder)
        seen = set()
        for lane in lanes:
            if lane.device in seen:
                continue
            seen.add(lane.device)
            start = 0
            arm_args = None
            for j, (size, valid) in enumerate(chunks):
                chunk = np.zeros((1, size), np.int32)
                lane.cache, lane.state, tok, done0 = self._prefill(
                    lane_params[lane.device], lane.cache, lane.state, chunk,
                    np.int32(0), np.int32(valid), np.int32(1),
                    jax.random.key(self.config.seed),
                    j == 0, j == len(chunks) - 1,
                )
                pages = self._extract(lane.cache.k, lane.cache.v,
                                      np.int32(start), size)
                pages_d = jax.device_put(pages, dsh)
                cache = self._insert(cache, pages_d[0], pages_d[1],
                                     np.int32(0), np.int32(start),
                                     np.int32(valid))
                start += valid
                if j == len(chunks) - 1:
                    arm_args = jax.device_put(
                        (tok, done0, lane.state.rng[0],
                         lane.state.history[0]), dsh)
            if arm_args is not None:
                tok, done0, carry, hist = arm_args
                state = self._arm(state, np.int32(0), tok, done0,
                                  np.int32(1), carry, hist)
                state = _release_step(state, np.int32(0))
        for _ in range(4 if mesh is not None else 1):
            cache, state, _, _, _ = self._decode(params, cache, state,
                                                 self._full_mask)
        return cache, state

    def _drain_decode_tick(self) -> None:
        """Advance every retired layout's surviving decodes by one step —
        the same compiled decode program, dispatched at the OLD placement
        (its cache entry already exists, so draining never compiles).
        Completions finish ``ok`` directly: a retired slot index must never
        reach the ACTIVE free list."""
        if not self._draining_layouts:
            return
        for L in list(self._draining_layouts):
            versions = sorted({r.weights_version
                               for r in L.decoding.values()})
            for v in versions:
                mask = np.zeros((self.n_slots,), bool)
                for slot, r in L.decoding.items():
                    if r.weights_version == v:
                        mask[slot] = True
                L.cache, L.state, toks, emitted, bad = self._decode(
                    L.params_by_version[v], L.cache, L.state, mask)
                self._stats["decode_steps"] += 1
                toks_np, emitted_np, done_np, bad_np = jax.device_get(
                    (toks, emitted, L.state.done, bad))
                for slot, req in list(L.decoding.items()):
                    if req.weights_version != v or not mask[slot]:
                        continue
                    if bool(bad_np[slot]):
                        del L.decoding[slot]
                        L.state = _release_step(L.state, np.int32(slot))
                        req.slot = None
                        self._retry_or_fail(
                            req, reason=("nonfinite logits while draining "
                                         f"layout {L.layout_id}"))
                        continue
                    cnt = int(emitted_np[slot])
                    for t in toks_np[slot, :cnt]:
                        req.out.append(int(t))
                    if self._speculate_k > 0 and cnt > 0:
                        req.spec_drafted += self._speculate_k
                        req.spec_accepted += max(cnt - 1, 0)
                    if bool(done_np[slot]):
                        del L.decoding[slot]
                        self._finish(req, "ok")
        self._prune_drained()

    def _prune_drained(self) -> None:
        alive = [L for L in self._draining_layouts if L.decoding]
        drained = len(self._draining_layouts) - len(alive)
        if drained:
            if self.tracing is not None:
                for L in self._draining_layouts:
                    if not L.decoding and L.trace_span is not None:
                        self.tracing.end(L.trace_span, self._stats["ticks"])
            self._draining_layouts = alive
            self._rstats["drained_layouts"] += drained
            if _log_ok():
                logger.info("disagg: %d retired layout(s) fully drained",
                            drained)

    def _extra_inflight(self) -> list:
        reqs = []
        for L in self._draining_layouts:
            reqs.extend(L.decoding.values())
        return reqs

    def _evict(self, req, status: str) -> None:
        """Drain-aware eviction: a request finishing on a retired layout
        releases THAT layout's row — the base path would free the same slot
        index in the ACTIVE layout, handing one slot to two requests."""
        for L in self._draining_layouts:
            if req.slot is not None and L.decoding.get(req.slot) is req:
                del L.decoding[req.slot]
                L.state = _release_step(L.state, np.int32(req.slot))
                self._finish(req, status)
                self._prune_drained()
                return
        super()._evict(req, status)

    # -- weight publication ------------------------------------------------

    def _install_params(self, params, version: int) -> None:
        """Disagg placement for a published version: ``params`` (validated
        against the decode placement — that is what ``_params`` aliases
        here) becomes the decode-mesh copy, plus one host of per-device
        copies for the prefill lanes, mirroring construction."""
        super()._install_params(params, version)
        by_dev: dict = {}
        for lane in self._lanes:
            if lane.device not in by_dev:
                by_dev[lane.device] = jax.device_put(params, lane.device)
        self._lane_params[int(version)] = by_dev

    def _drop_params(self, version: int) -> None:
        super()._drop_params(version)
        self._lane_params.pop(int(version), None)

    # -- warmup ------------------------------------------------------------

    def warmup(self) -> None:
        """Compile the full two-mesh program set before real traffic: one
        rung-walking request PER LANE (jit caches per placement, so every
        lane device must see every ladder rung — prefill and extract alike),
        which also compiles the per-rung inserts, the arm, and the decode
        step. FIFO lane reuse guarantees coverage even when slots are
        scarcer than lanes. Metrics reset afterwards."""
        prompt_len = min(sum(self.ladder), self.t_max - 2)
        prompt = np.ones((prompt_len,), np.int32)
        self.run([prompt] * len(self._lanes), max_new_tokens=2)
        self.reset_metrics()

    def reset_metrics(self) -> None:
        super().reset_metrics()
        for k in self._hstats:
            self._hstats[k] = 0
        for k in self._rstats:
            self._rstats[k] = 0.0 if k == "transfer_wall_s" else 0
        self._handoff_lat_s.clear()

    # -- reporting ---------------------------------------------------------

    def executable_counts(self) -> dict:
        """Adds the data-plane programs to the base census. ``prefill`` is
        now bounded by ``len(ladder) * n_prefill_devices`` (jit compiles
        per placement); ``decode`` stays exactly 1 — the placement is
        fixed, so the invariant survives the split."""
        out = super().executable_counts()
        out["handoff_extract"] = _cache_size(self._extract)
        out["handoff_insert"] = _cache_size(self._insert)
        out["slot_arm"] = _cache_size(self._arm)
        return out

    def stats(self) -> dict:
        out = super().stats()
        hs = self._hstats
        lat = np.asarray(self._handoff_lat_s, np.float64)
        s = self._stats
        measured = (s["prompt_tokens_in"] / s["tokens_out"]
                    if s["tokens_out"] else None)
        out["disagg"] = {
            "slice_plan": self.slice_plan.to_dict(),
            "n_prefill_devices": len(self.prefill_devices),
            "n_decode_devices": len(self.decode_devices),
            "decode_slot_sharded": self._decode_mesh is not None,
            "n_prefill_lanes": len(self._lanes),
            "handoff_depth": int(self.disagg_config.handoff_depth),
            "handoff_transfers": hs["transfers"],
            "handoff_inserts": hs["inserts"],
            "handoff_bytes": hs["bytes"],
            "handoff_final_flushes": hs["flushes"],
            "handoff_lat_sampled": int(lat.size),
            "handoff_lat_mean_s": float(lat.mean()) if lat.size else None,
            "handoff_lat_p95_s": (
                float(np.percentile(lat, 95)) if lat.size else None),
            "quarantined_lanes": sorted(self._quarantined_lanes),
            "healthy_lanes": len(self._lanes) - len(self._quarantined_lanes),
            "degraded": bool(self._degraded),
            # The ratio to feed back into DisaggConfig for the next run —
            # the calibration loop the planner's cost model expects.
            "measured_flop_ratio": (
                round(measured, 6) if measured is not None else None),
        }
        rs = dict(self._rstats)
        rs["transfer_wall_s"] = round(rs["transfer_wall_s"], 6)
        rs["active_layout"] = self._active_layout_id
        rs["n_devices"] = len(self._devices)
        rs["draining_layouts"] = len(self._draining_layouts)
        rs["draining_requests"] = sum(
            len(L.decoding) for L in self._draining_layouts)
        out["disagg"]["resize"] = rs
        return out

    def _push_telemetry_summary(self) -> None:
        super()._push_telemetry_summary()  # serving block (incl. "disagg")
        if self.telemetry is not None:
            try:
                self.telemetry.record_disagg(self.stats()["disagg"])
            except Exception as e:  # observability must never kill serving
                logger.warning_once(f"disagg: telemetry summary failed: {e}")
