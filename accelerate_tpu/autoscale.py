"""Telemetry-driven autoscaling for the serving stack (layer L7).

The repo already has every piece of a self-operating engine — SLO-aware
admission (serving.py), planner-sized disaggregation (disagg.py), elastic
redistribution (resharding.py), and a zero-downtime param-swap seam
(publish.py) — but nothing closes the loop: the engine rides a fixed
prefill/decode split while queue depth, shed rates, and TTFT percentiles
are recorded and ignored. This module is that loop, deliberately boring:

- **Signals** — rolling-window (NOT lifetime) SLO aggregates from
  ``ServingEngine.window_stats()``: queue depth p95, shed/timeout rates,
  ok-only TTFT p95, and the observed prompt:decode ratio, sampled every
  ``poll_ticks`` engine ticks.
- **Decisions** — hysteresis bands around the targets
  (``queue_depth_high``/``queue_depth_low``), ``breach_samples``
  consecutive breached samples before acting, and ``cooldown_ticks`` after
  every resize: one noisy sample (or an injected ``flap`` fault) can never
  move the topology. Any proposed world size passes the SAME planner gate
  as the gang supervisor's dead-host shrink
  (:func:`~accelerate_tpu.planner.validate_world_size`, via
  :func:`~accelerate_tpu.resharding.grow_world_size` /
  :func:`~accelerate_tpu.resharding.shrink_world_size`) plus a
  :func:`~accelerate_tpu.planner.plan_disagg_slices` consult under the
  window's observed ratio. Every decision — including "hold" — lands in
  ``history`` and telemetry naming the triggering signal.
- **Actuation** — :meth:`DisaggServingEngine.resize`: the whole target
  layout is built and pre-warmed before a one-swap commit, in-flight
  decodes drain on the retired layout, and a failed resize aborts with the
  old layout untouched.

Determinism: every signal the policy reads is tick-deterministic (queue
depth, terminal-status rates, token ratios) — never wall-clock — so a
seeded trace replays the exact decision/resize sequence bit-identically
(the ``make autoscale-smoke`` bar). ``ttft_p95_slo_s`` is the one
wall-clock knob; it defaults to None (advisory, recorded in every
decision) and turning it on trades replay determinism for latency-reactive
scaling — the docstring on :class:`AutoscaleConfig` says so.

Off by default everywhere: nothing constructs a controller unless you do
(or call ``Accelerator.build_autoscale_controller``).

Usage::

    from accelerate_tpu import AutoscaleConfig, AutoscaleController

    engine = DisaggServingEngine(model, cfg, disagg=dc, devices=pool[:4])
    auto = AutoscaleController(engine, AutoscaleConfig(poll_ticks=16),
                               device_pool=pool)
    while engine.pending:
        engine.tick()
        auto.poll()                   # samples + decides every poll_ticks
    auto.mark_device_dead(pool[2])    # health-check path: immediate shrink
    auto.stats()                      # decisions/holds/grows/shrinks/aborts
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from .logging import get_logger
from .planner import PlannerError, plan_disagg_slices, validate_world_size
from .resharding import grow_world_size, shrink_world_size

logger = get_logger(__name__)

__all__ = ["AutoscaleConfig", "AutoscaleController", "make_diurnal_trace"]


def _log_ok() -> bool:
    from .state import PartialState

    return bool(PartialState._shared_state)


@dataclasses.dataclass
class AutoscaleConfig:
    """Policy knobs for :class:`AutoscaleController`. The defaults are
    deliberately conservative: two consecutive breached samples to act, a
    long cooldown after every resize, and a bounded resize budget — an
    autoscaler that flaps is worse than none.

    - ``poll_ticks``: engine ticks between samples (the window needs time
      to move between readings).
    - ``window_min_requests``: hold (``window_thin``) until the rolling
      window holds at least this many terminal requests.
    - ``queue_depth_high`` / ``queue_depth_low``: the hysteresis band
      around the queue-depth p95 signal — above the high edge reads as
      overload (grow), below the low edge as idle capacity (shrink),
      between them the topology holds. Any window shedding above
      ``shed_rate_high`` also reads as overload.
    - ``breach_samples``: consecutive breached samples required before a
      resize — one noisy sample (or an injected ``flap``) is damped.
    - ``cooldown_ticks``: no load-driven resize within this many ticks of
      the previous one (dead-device shrinks are correctness, not load, and
      skip the cooldown).
    - ``resplit_tolerance``: relative drift between the window's observed
      prompt:decode ratio and the active plan's before an in-place
      re-split is considered.
    - ``min_devices`` / ``max_devices`` / ``max_resizes``: hard bounds on
      the actuator (disaggregation needs >= 2 devices).
    - ``layout``: recorded parallel layout handed to the shared
      :func:`~accelerate_tpu.planner.validate_world_size` gate.
    - ``ttft_p95_slo_s``: optional wall-clock TTFT SLO. None (default)
      keeps decisions fully tick-deterministic — the value is still
      recorded in every decision for observability; setting it makes a
      window TTFT p95 above it read as overload, trading bit-identical
      replay for latency-reactive scaling.
    """

    poll_ticks: int = 16
    window_min_requests: int = 8
    queue_depth_high: float = 4.0
    queue_depth_low: float = 0.5
    shed_rate_high: float = 0.0
    breach_samples: int = 2
    cooldown_ticks: int = 64
    resplit_tolerance: float = 0.5
    min_devices: int = 2
    max_devices: Optional[int] = None
    max_resizes: Optional[int] = None
    layout: Optional[dict] = None
    ttft_p95_slo_s: Optional[float] = None

    def __post_init__(self):
        if self.poll_ticks < 1:
            raise ValueError("poll_ticks must be >= 1")
        if self.window_min_requests < 1:
            raise ValueError("window_min_requests must be >= 1")
        if not 0 <= self.queue_depth_low < self.queue_depth_high:
            raise ValueError(
                "need 0 <= queue_depth_low < queue_depth_high, got "
                f"{self.queue_depth_low} / {self.queue_depth_high}"
            )
        if self.shed_rate_high < 0:
            raise ValueError("shed_rate_high must be >= 0")
        if self.breach_samples < 1:
            raise ValueError("breach_samples must be >= 1")
        if self.cooldown_ticks < 0:
            raise ValueError("cooldown_ticks must be >= 0")
        if not self.resplit_tolerance > 0:
            raise ValueError("resplit_tolerance must be > 0")
        if self.min_devices < 2:
            raise ValueError("min_devices must be >= 2 (disaggregation "
                             "needs a prefill and a decode slice)")
        if self.max_devices is not None and self.max_devices < self.min_devices:
            raise ValueError("max_devices must be >= min_devices (or None)")
        if self.max_resizes is not None and self.max_resizes < 0:
            raise ValueError("max_resizes must be >= 0 (or None)")
        if self.ttft_p95_slo_s is not None and not self.ttft_p95_slo_s > 0:
            raise ValueError("ttft_p95_slo_s must be > 0 (or None)")


class AutoscaleController:
    """Closes the telemetry → planner → live-resize loop over one
    :class:`~accelerate_tpu.disagg.DisaggServingEngine`. Call
    :meth:`poll` between engine ticks (it is a no-op except every
    ``poll_ticks``); call :meth:`mark_device_dead` from a health check to
    shrink off a lost device immediately. OFF unless constructed — the
    engine never resizes itself."""

    def __init__(self, engine, config: Optional[AutoscaleConfig] = None, *,
                 device_pool=None, chaos=None, telemetry=None, tracing=None):
        if not hasattr(engine, "resize"):
            raise ValueError(
                "AutoscaleController needs an engine with a live resize "
                "actuator (DisaggServingEngine); the colocated ServingEngine "
                "has no topology to re-split."
            )
        self.engine = engine
        self.config = config if config is not None else AutoscaleConfig()
        self.chaos = chaos
        self.telemetry = telemetry
        # Share the engine/telemetry trace recorder so autoscale decisions
        # appear on the same timeline as the resize spans they trigger.
        self.tracing = tracing
        if self.tracing is None:
            self.tracing = getattr(telemetry, "tracing", None)
        if self.tracing is None:
            self.tracing = getattr(engine, "tracing", None)
        if self.tracing is not None:
            self.tracing.register_gauges("autoscale", self.stats)
        pool = (list(device_pool) if device_pool is not None
                else list(engine._devices))
        for d in engine._devices:
            if d not in pool:
                raise ValueError(
                    f"engine device {d} is not in the controller's device "
                    "pool — the pool must cover the active set"
                )
        self.pool = pool
        self.dead: set = set()
        self.history: list[dict] = []
        self._last_sample_tick: Optional[int] = None
        self._breach_over = 0
        self._breach_under = 0
        self._cooldown_until = 0
        self._stats = {
            "samples": 0, "decisions": 0, "holds": 0, "grows": 0,
            "shrinks": 0, "resplits": 0, "dead_device_shrinks": 0,
            "resizes": 0, "aborts": 0, "flap_damped": 0, "spikes": 0,
            "planner_refusals": 0,
        }

    # -- signals -----------------------------------------------------------

    def poll(self) -> Optional[dict]:
        """Sample the rolling window and decide, once per ``poll_ticks``
        engine ticks. Returns the decision record (also appended to
        ``history``) on sampling ticks, None otherwise."""
        tick = int(self.engine._stats["ticks"])
        c = self.config
        last = self._last_sample_tick
        if (last is None and tick < c.poll_ticks) or \
                (last is not None and tick - last < c.poll_ticks):
            return None
        self._last_sample_tick = tick
        return self._decide(tick, self._sample(tick))

    def _sample(self, tick: int) -> dict:
        self._stats["samples"] += 1
        w = self.engine.window_stats()
        sample = {
            "tick": tick,
            "requests": int(w["requests"]),
            "queue_depth_p95": float(w["queue_depth_p95"] or 0.0),
            "shed_rate": float(w["shed_rate"]),
            "timeout_rate": float(w["timeout_rate"]),
            "ttft_p95_s": w["ttft_p95_s"],
            "prompt_decode_ratio": w["prompt_decode_ratio"],
            "spike": False,
        }
        if self.chaos is not None:
            fault = self.chaos.draw("load_spike", tick, unit=0)
            if fault is not None and fault.kind == "spike":
                # A synthetic spike: the sample reads as hard overload. The
                # decision path downstream is the REAL grow path — damping,
                # planner consult, resize — exercised without real load.
                self._stats["spikes"] += 1
                sample["spike"] = True
                sample["queue_depth_p95"] = max(
                    sample["queue_depth_p95"],
                    4.0 * float(self.config.queue_depth_high))
        return sample

    # -- decisions ---------------------------------------------------------

    def _decide(self, tick: int, sample: dict) -> dict:
        c = self.config
        qd = sample["queue_depth_p95"]
        ttft_breach = (c.ttft_p95_slo_s is not None
                       and sample["ttft_p95_s"] is not None
                       and sample["ttft_p95_s"] > c.ttft_p95_slo_s)
        over = (qd > c.queue_depth_high
                or sample["shed_rate"] > c.shed_rate_high or ttft_breach)
        under = (qd < c.queue_depth_low
                 and sample["shed_rate"] <= c.shed_rate_high
                 and sample["timeout_rate"] == 0.0 and not ttft_breach)
        if over:
            signal = ("shed_rate" if sample["shed_rate"] > c.shed_rate_high
                      else "ttft_p95_s" if ttft_breach else "queue_depth_p95")
        elif under:
            signal = "queue_depth_p95"
        else:
            signal = "in_band"
        flap = False
        if self.chaos is not None:
            fault = self.chaos.draw("autoscale_decide", tick, unit=0)
            if fault is not None and fault.kind == "flap":
                # The injected flap inverts this ONE sample's band reading;
                # only the consecutive-breach damper stands between it and
                # a spurious resize.
                over, under = under, over
                flap = True
                signal = f"flap({signal})"

        if sample["requests"] < c.window_min_requests:
            self._breach_over = self._breach_under = 0
            return self._record(
                tick, "hold", "window_thin", sample, flap=flap,
                reason=(f"window holds {sample['requests']} < "
                        f"{c.window_min_requests} requests"))
        self._breach_over = self._breach_over + 1 if over else 0
        self._breach_under = self._breach_under + 1 if under else 0
        in_cooldown = tick < self._cooldown_until

        if over and self._breach_over >= c.breach_samples:
            if in_cooldown:
                return self._record(
                    tick, "hold", signal, sample, flap=flap,
                    reason=f"cooldown until tick {self._cooldown_until}")
            return self._try_grow(tick, signal, sample, flap)
        if under and self._breach_under >= c.breach_samples:
            if in_cooldown:
                return self._record(
                    tick, "hold", signal, sample, flap=flap,
                    reason=f"cooldown until tick {self._cooldown_until}")
            return self._try_shrink(tick, signal, sample, flap)
        if over or under:
            n = self._breach_over if over else self._breach_under
            return self._record(
                tick, "hold", signal, sample, flap=flap,
                reason=(f"breach {n}/{c.breach_samples} consecutive "
                        "samples — damped"))
        if not in_cooldown:
            resplit = self._maybe_resplit(tick, sample, flap)
            if resplit is not None:
                return resplit
        return self._record(tick, "hold", signal, sample, flap=flap,
                            reason="signals inside the hysteresis band")

    def _resize_budget_spent(self) -> bool:
        return (self.config.max_resizes is not None
                and self._stats["resizes"] >= self.config.max_resizes)

    def _ratio(self, sample: dict) -> float:
        r = sample.get("prompt_decode_ratio")
        return float(r) if r else float(self.engine.slice_plan.flop_ratio)

    def _pick_devices(self, n: int) -> list:
        """Target device set: keep the current (surviving) set stable,
        extend from the pool's spares — minimizes what the resize moves."""
        cur = [d for d in self.engine._devices if d not in self.dead]
        extra = [d for d in self.pool
                 if d not in self.dead and d not in cur]
        return (cur + extra)[:n]

    def _consult_planner(self, n: int, ratio: float) -> Optional[str]:
        """The shared topology gate every proposal passes BEFORE the
        actuator is touched: the world size must validate
        (:func:`planner.validate_world_size`, same helper as the gang
        supervisor's dead-host shrink) and the disagg split must plan
        under the observed ratio. Returns a refusal reason or None."""
        if not validate_world_size(n, self.config.layout):
            return f"validate_world_size refused {n} devices"
        try:
            plan_disagg_slices(n, prefill_decode_flop_ratio=ratio)
        except PlannerError as e:
            return f"planner refused {n} devices: {e}"
        return None

    def _try_grow(self, tick: int, signal: str, sample: dict,
                  flap: bool) -> dict:
        c = self.config
        if self._resize_budget_spent():
            return self._record(tick, "hold", signal, sample, flap=flap,
                                reason=f"resize budget ({c.max_resizes}) spent")
        n_active = len(self.engine._devices)
        avail = [d for d in self.pool if d not in self.dead]
        cap = min(len(avail), c.max_devices or len(avail))
        if cap - n_active <= 0:
            return self._record(tick, "hold", signal, sample, flap=flap,
                                reason="no spare devices in the pool")
        target = grow_world_size(n_active, gained=cap - n_active,
                                 layout=c.layout)
        if target is None or target > cap:
            self._stats["planner_refusals"] += 1
            return self._record(
                tick, "hold", signal, sample, flap=flap,
                reason=f"no viable larger size above {n_active}")
        ratio = self._ratio(sample)
        refused = self._consult_planner(target, ratio)
        if refused:
            self._stats["planner_refusals"] += 1
            return self._record(tick, "hold", signal, sample, flap=flap,
                                reason=refused)
        return self._actuate(tick, "grow", signal, sample, flap,
                             self._pick_devices(target), ratio)

    def _try_shrink(self, tick: int, signal: str, sample: dict,
                    flap: bool) -> dict:
        c = self.config
        if self._resize_budget_spent():
            return self._record(tick, "hold", signal, sample, flap=flap,
                                reason=f"resize budget ({c.max_resizes}) spent")
        n_active = len(self.engine._devices)
        target = shrink_world_size(n_active, lost=1, layout=c.layout)
        if target is None or target < c.min_devices:
            return self._record(
                tick, "hold", signal, sample, flap=flap,
                reason=f"already at min_devices ({n_active} active)")
        ratio = self._ratio(sample)
        refused = self._consult_planner(target, ratio)
        if refused:
            self._stats["planner_refusals"] += 1
            return self._record(tick, "hold", signal, sample, flap=flap,
                                reason=refused)
        return self._actuate(tick, "shrink", signal, sample, flap,
                             self._pick_devices(target), ratio)

    def _maybe_resplit(self, tick: int, sample: dict,
                       flap: bool) -> Optional[dict]:
        """In-band and out of cooldown: if the window's observed
        prompt:decode ratio drifted past ``resplit_tolerance`` AND the
        planner wants a different split at the SAME device count, re-split
        in place. Returns None when there is nothing to do (the common
        case — the caller then records a plain hold)."""
        ratio = sample.get("prompt_decode_ratio")
        if not ratio or self._resize_budget_spent():
            return None
        cur = float(self.engine.slice_plan.flop_ratio)
        if abs(float(ratio) - cur) / max(cur, 1e-9) <= \
                self.config.resplit_tolerance:
            return None
        n_active = len(self.engine._devices)
        try:
            plan = plan_disagg_slices(
                n_active, prefill_decode_flop_ratio=float(ratio))
        except PlannerError:
            return None
        if plan.n_prefill == self.engine.slice_plan.n_prefill:
            return None
        return self._actuate(tick, "resplit", "prompt_decode_ratio", sample,
                             flap, self._pick_devices(n_active),
                             float(ratio))

    # -- actuation ---------------------------------------------------------

    def _actuate(self, tick: int, action: str, signal: str, sample: dict,
                 flap: bool, devices: list, ratio: float) -> dict:
        rec = self.engine.resize(devices=devices, flop_ratio=ratio,
                                 dead_devices=self.dead)
        self._cooldown_until = tick + int(self.config.cooldown_ticks)
        self._breach_over = self._breach_under = 0
        if rec.get("ok"):
            self._stats["resizes"] += 1
            self._stats[{"grow": "grows", "shrink": "shrinks",
                         "resplit": "resplits"}[action]] += 1
            reason = (f"{signal} breached {self.config.breach_samples} "
                      f"consecutive samples" if action != "resplit" else
                      f"observed ratio {ratio:.3g} vs plan "
                      f"{self.engine.slice_plan.flop_ratio:.3g}")
            return self._record(tick, action, signal, sample, flap=flap,
                                reason=reason, resize=rec)
        self._stats["aborts"] += 1
        return self._record(tick, f"{action}_aborted", signal, sample,
                            flap=flap, reason=rec.get("reason", "resize "
                            "aborted"), resize=rec)

    def mark_device_dead(self, device) -> Optional[dict]:
        """Health-check path: ``device`` is gone. A dead ACTIVE device
        shrinks immediately — correctness, not load, so neither the
        cooldown nor the breach damper applies (the resize budget still
        does not: survival beats quota). The surviving exact count is used
        when the shared planner gate validates it, else the largest viable
        smaller size. A dead spare is only recorded.

        "Gone" includes silently WRONG: the decode canary
        (:class:`~accelerate_tpu.sdc.DecodeCanary`) routes a bit-wise
        output mismatch through this same correctness-shrink, so a chip
        producing finite-but-corrupt tokens is excised exactly like one
        that stopped answering."""
        self.dead.add(device)
        tick = int(self.engine._stats["ticks"])
        if device not in self.engine._devices:
            return self._record(
                tick, "hold", "dead_device", None,
                reason=f"dead device {device} was a spare")
        n_active = len(self.engine._devices)
        survivors = n_active - 1
        if validate_world_size(survivors, self.config.layout) and \
                self._consult_planner(
                    survivors, float(self.engine.slice_plan.flop_ratio)) is None:
            target = survivors
        else:
            target = shrink_world_size(n_active, lost=1,
                                       layout=self.config.layout)
        if target is None or target < 2:
            self._stats["planner_refusals"] += 1
            return self._record(
                tick, "hold", "dead_device", None,
                reason=(f"no viable size below {n_active} — engine keeps "
                        "serving degraded"))
        rec = self.engine.resize(devices=self._pick_devices(target),
                                 dead_devices=self.dead)
        self._cooldown_until = tick + int(self.config.cooldown_ticks)
        self._breach_over = self._breach_under = 0
        if rec.get("ok"):
            self._stats["resizes"] += 1
            self._stats["shrinks"] += 1
            self._stats["dead_device_shrinks"] += 1
            return self._record(tick, "shrink", "dead_device", None,
                                reason=f"device {device} died", resize=rec)
        self._stats["aborts"] += 1
        return self._record(tick, "shrink_aborted", "dead_device", None,
                            reason=rec.get("reason", "resize aborted"),
                            resize=rec)

    # -- reporting ---------------------------------------------------------

    def _record(self, tick: int, action: str, signal: str,
                sample: Optional[dict], *, reason: str, flap: bool = False,
                resize: Optional[dict] = None) -> dict:
        self._stats["decisions"] += 1
        if action == "hold":
            self._stats["holds"] += 1
            if flap:
                # The flap fired and nothing moved — the damper absorbed it.
                self._stats["flap_damped"] += 1
        rec = {
            "tick": tick, "action": action, "signal": signal,
            "reason": reason, "flap_injected": flap,
            "active_devices": len(self.engine._devices),
        }
        if sample is not None:
            rec["sample"] = dict(sample)
        if resize is not None:
            rec["resize"] = dict(resize)
        self.history.append(rec)
        if self.tracing is not None and action != "hold":
            self.tracing.instant(
                "autoscale", f"autoscale_{action}", tick, signal=signal,
                reason=reason, active_devices=rec["active_devices"])
        # Decisions already land in telemetry records and trace instants;
        # under flapping load this fires every few ticks, so keep it at
        # debug rather than spamming INFO on the serving hot path.
        if _log_ok() and action != "hold":
            logger.debug("autoscale: tick %d %s (%s — %s)", tick, action,
                         signal, reason)
        if self.telemetry is not None:
            try:
                self.telemetry.record_event(
                    "autoscale_decision", tick=tick, action=action,
                    signal=signal, reason=reason, flap_injected=flap,
                    active_devices=rec["active_devices"],
                    ttft_p95_slo_s=self.config.ttft_p95_slo_s,
                )
            except Exception:
                pass  # observability must never kill the control loop
        return rec

    def stats(self) -> dict:
        """The ``autoscale`` telemetry block: decision/resize counters plus
        the live control state (bench rows and ``make autoscale-smoke``
        embed this verbatim)."""
        out = dict(self._stats)
        out["active_devices"] = len(self.engine._devices)
        out["pool_devices"] = len(self.pool)
        out["dead_devices"] = len(self.dead)
        out["cooldown_until_tick"] = self._cooldown_until
        out["breach_over"] = self._breach_over
        out["breach_under"] = self._breach_under
        last = next((h for h in reversed(self.history)
                     if h["action"] != "hold"), None)
        out["last_action"] = (
            {k: last[k] for k in ("tick", "action", "signal", "reason")}
            if last is not None else None)
        return out

    def close(self) -> None:
        """Flush the autoscale summary into the telemetry stream."""
        if self.telemetry is not None:
            try:
                self.telemetry.record_autoscale(self.stats())
            except Exception as e:
                logger.warning_once(f"autoscale: telemetry summary failed: {e}")


# ---------------------------------------------------------------------------
# Seeded diurnal load trace (shared by benchmarks and the autoscale smoke)
# ---------------------------------------------------------------------------


def make_diurnal_trace(n_requests: int = 64, *, seed: int = 0,
                       swing: float = 10.0, base_rate: float = 1.0,
                       short_prompt=(8, 24), long_prompt=(32, 64),
                       short_budget=(4, 8), long_budget=(12, 24),
                       vocab_size: int = 256) -> dict:
    """Deterministic diurnal arrival trace: three plateaus (low, high, low
    — a compressed day) whose offered rate swings by ``swing``x and whose
    prompt:decode mix shifts with it (the high plateau sends long prompts
    with short continuations — prefill-heavy; the low plateaus the
    opposite), so an autoscaler must both grow AND re-split to ride it.
    Everything is drawn from one seeded generator: the same seed yields
    the same arrivals, prompts, and budgets, byte for byte.

    Returns ``{"arrivals", "phases", "prompts", "budgets", "lengths"}`` —
    arrivals in abstract time units (scale by your tick or wall-clock
    rate), phases 0/1/2 per request."""
    rng = np.random.default_rng(seed)
    n = int(n_requests)
    if n < 4:
        raise ValueError("n_requests must be >= 4 (three plateaus)")
    n1 = n // 4
    n2 = n // 2
    phases = np.concatenate([
        np.zeros(n1, np.int64), np.ones(n2, np.int64),
        np.full(n - n1 - n2, 2, np.int64),
    ])
    rates = np.where(phases == 1, float(base_rate) * float(swing),
                     float(base_rate))
    arrivals = np.cumsum(rng.exponential(1.0, n) / rates)
    lengths = np.empty(n, np.int64)
    budgets = np.empty(n, np.int64)
    for i in range(n):
        plo, phi = long_prompt if phases[i] == 1 else short_prompt
        blo, bhi = short_budget if phases[i] == 1 else long_budget
        lengths[i] = rng.integers(plo, phi + 1)
        budgets[i] = rng.integers(blo, bhi + 1)
    prompts = [rng.integers(1, int(vocab_size), (int(L),), dtype=np.int32)
               for L in lengths]
    return {"arrivals": arrivals, "phases": phases, "prompts": prompts,
            "budgets": [int(b) for b in budgets],
            "lengths": [int(x) for x in lengths]}
