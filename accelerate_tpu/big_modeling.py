"""Big-model inference: load + run models larger than one chip's HBM.

TPU-native redesign of the reference's hook machinery (reference:
big_modeling.py:62-662, hooks.py:242-719). The reference intercepts every
``module.forward`` with ``AlignDevicesHook``s that fault weights in from
CPU/disk and evict them after. Python-per-module hooks would destroy XLA
fusion, so the equivalent here is *layer streaming*:

- params live where the device map put them (HBM / host numpy / disk memmap);
- the forward walks the model's layer stream plan, keeping at most two
  decoder blocks resident: while block *i* computes on the chip, block
  *i+1*'s weights ride the DMA in parallel (``jax.device_put`` is async),
  which is the role of the reference's ``AlignDevicesHook`` prefetch;
- each block reuses ONE jitted computation (identical shapes ⇒ one compile),
  the same trick as the reference's regional compilation
  (utils/other.py:106-177).

Models without a registered stream plan fall back to materialize-per-call
(exactly the reference's ``cpu_offload`` semantics, big_modeling.py:179-231).
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .model import Model
from .utils.modeling import (
    _DiskHandle,
    check_device_map,
    compute_abstract_params,
    default_execution_device,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    normalize_device_map,
    placement_for,
)
from .utils.offload import offload_state_dict
from .utils.other import flatten_state_dict, unflatten_state_dict

__all__ = [
    "init_empty_weights",
    "init_on_device",
    "cpu_offload",
    "cpu_offload_with_hook",
    "disk_offload",
    "dispatch_model",
    "load_checkpoint_and_dispatch",
    "DispatchedModel",
    "UserCpuOffloadHook",
    "register_stream_plan",
]


def init_empty_weights(module, *sample_args, rng=None, **sample_kwargs):
    """Abstract-shape init — zero bytes allocated.

    The functional counterpart of the reference's meta-device context manager
    (big_modeling.py:62-178): returns a pytree of ``jax.ShapeDtypeStruct``
    describing ``module.init``'s params.
    """
    return compute_abstract_params(module, *sample_args, rng=rng, **sample_kwargs)


def init_on_device(device):
    """Context manager placing array creation (``module.init`` included) on
    ``device`` — host RAM via ``jax.local_devices(backend="cpu")[0]`` for
    models that must not touch HBM during init (reference:
    big_modeling.py:116-178 ``init_on_device``)."""
    return jax.default_device(device)


# ---------------------------------------------------------------------------
# Param resolver: faults groups in from their placement, with async prefetch
# ---------------------------------------------------------------------------


class ParamResolver:
    """Materialize param subtrees on the execution device on demand.

    ``prefetch`` enqueues the H2D copy immediately and returns; ``take``
    hands the arrays over and evicts them from the cache once consumed —
    together they give the double-buffered pipeline the reference builds
    with hook ``pre_forward``/``post_forward`` pairs (hooks.py:358-431).
    """

    def __init__(self, placed_params, device, sep: str = "/"):
        self.placed = placed_params
        self.device = device
        self.sep = sep
        self._cache: dict[str, Any] = {}

    def _subtree(self, prefix: str):
        node = self.placed
        for part in prefix.split(self.sep):
            node = node[part]
        return node

    def _materialize(self, node, layer_index: Optional[int] = None):
        def _leaf(a):
            if isinstance(a, _DiskHandle):
                a = a.load()
            if layer_index is not None:
                a = a[layer_index]
            if isinstance(a, jax.Array) and a.devices() == {self.device}:
                return a
            return jax.device_put(np.asarray(a) if isinstance(a, np.memmap) else a, self.device)

        return jax.tree.map(_leaf, node)

    def _key(self, prefix, layer_index):
        return prefix if layer_index is None else f"{prefix}@{layer_index}"

    def prefetch(self, prefix: str, layer_index: Optional[int] = None):
        key = self._key(prefix, layer_index)
        if key not in self._cache:
            self._cache[key] = self._materialize(self._subtree(prefix), layer_index)

    def take(self, prefix: str, layer_index: Optional[int] = None):
        key = self._key(prefix, layer_index)
        if key in self._cache:
            return self._cache.pop(key)
        return self._materialize(self._subtree(prefix), layer_index)

    def peek(self, prefix: str, layer_index: Optional[int] = None):
        """Like take but keeps resident (for groups already living on device)."""
        key = self._key(prefix, layer_index)
        if key not in self._cache:
            self._cache[key] = self._materialize(self._subtree(prefix), layer_index)
        return self._cache[key]


# ---------------------------------------------------------------------------
# Stream plans (per model family)
# ---------------------------------------------------------------------------

_STREAM_PLANS: dict[str, Callable] = {}
_JIT_CACHE: dict[Any, Callable] = {}


def register_stream_plan(module_class_name: str, fn: Callable):
    """Register ``fn(module, resolver, *args) -> output`` as the streamed
    forward for a model family."""
    _STREAM_PLANS[module_class_name] = fn


def _jit_for(key, fn):
    if key not in _JIT_CACHE:
        _JIT_CACHE[key] = jax.jit(fn)
    return _JIT_CACHE[key]


def _llama_stream_forward(module, resolver: ParamResolver, input_ids):
    """Layer-streamed Llama forward: ≤2 blocks resident in HBM at once."""
    import flax.linen as nn

    from .models.llama import LlamaBlock, RMSNorm

    cfg = module.config
    input_ids = jnp.asarray(input_ids)

    embed = nn.Embed(
        cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
        name="embed_tokens",
    )
    # peek (not take) when tied: the table is reused by the head, one upload.
    embed_params = (
        resolver.peek("model/embed_tokens")
        if cfg.tie_word_embeddings
        else resolver.take("model/embed_tokens")
    )
    x = _jit_for((cfg, "embed"), lambda p, ids: embed.apply({"params": p}, ids))(
        embed_params, input_ids
    )
    positions = jnp.broadcast_to(
        jnp.arange(input_ids.shape[-1], dtype=jnp.int32)[None, :], input_ids.shape
    )

    block = LlamaBlock(cfg)
    block_fn = _jit_for((cfg, "block"), lambda p, h, pos: block.apply({"params": p}, h, pos))
    if cfg.scan_layers:
        layer_args = [("model/layers/block", i) for i in range(cfg.num_hidden_layers)]
    else:
        layer_args = [(f"model/layers_{i}", None) for i in range(cfg.num_hidden_layers)]

    resolver.prefetch(*layer_args[0])
    for i, (prefix, idx) in enumerate(layer_args):
        if i + 1 < len(layer_args):
            resolver.prefetch(*layer_args[i + 1])  # DMA overlaps block i's compute
        x = block_fn(resolver.take(prefix, idx), x, positions)

    norm = RMSNorm(cfg.rms_norm_eps)
    x = _jit_for((cfg, "norm"), lambda p, h: norm.apply({"params": p}, h))(
        resolver.take("model/norm"), x
    )
    if cfg.tie_word_embeddings:
        w = resolver.take("model/embed_tokens")["embedding"]  # still cached from embed step
        return _jit_for((cfg, "tied_head"), lambda w, h: h @ w.T.astype(cfg.dtype))(w, x)
    head = resolver.take("lm_head")
    return _jit_for((cfg, "head"), lambda p, h: (h @ p["kernel"].astype(cfg.dtype)))(head, x)


register_stream_plan("LlamaForCausalLM", _llama_stream_forward)


def _opt_stream_forward(module, resolver: ParamResolver, input_ids):
    """Layer-streamed OPT forward — the reference's OPT-30B big-model-inference
    workload (benchmarks/big_model_inference/README.md) with ≤2 blocks in HBM."""
    import flax.linen as nn

    from .models.opt import OPTBlock

    cfg = module.config
    input_ids = jnp.asarray(input_ids)

    embed_params = resolver.peek("model/embed_tokens")  # reused by the tied head
    embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32)
    x = _jit_for((cfg, "embed"), lambda p, ids: embed.apply({"params": p}, ids))(
        embed_params, input_ids
    )
    pos_embed = nn.Embed(
        cfg.max_position_embeddings + cfg.POSITION_OFFSET, cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=jnp.float32,
    )
    positions = jnp.arange(input_ids.shape[-1]) + cfg.POSITION_OFFSET
    x = x + _jit_for((cfg, "pos"), lambda p, i: pos_embed.apply({"params": p}, i))(
        resolver.take("model/embed_positions"), positions
    )

    block = OPTBlock(cfg)
    block_fn = _jit_for((cfg, "block"), lambda p, h: block.apply({"params": p}, h))
    if cfg.scan_layers:
        layer_args = [("model/layers/block", i) for i in range(cfg.num_hidden_layers)]
    else:
        layer_args = [(f"model/layer_{i}", None) for i in range(cfg.num_hidden_layers)]
    resolver.prefetch(*layer_args[0])
    for i, (prefix, idx) in enumerate(layer_args):
        if i + 1 < len(layer_args):
            resolver.prefetch(*layer_args[i + 1])
        x = block_fn(resolver.take(prefix, idx), x)

    ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
    x = _jit_for((cfg, "ln_f"), lambda p, h: ln.apply({"params": p}, h))(
        resolver.take("model/final_layer_norm"), x
    )
    w = resolver.take("model/embed_tokens")["embedding"]
    return _jit_for((cfg, "tied_head"), lambda w, h: (h @ w.T.astype(cfg.dtype)))(w, x)


register_stream_plan("OPTForCausalLM", _opt_stream_forward)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


class DispatchedModel(Model):
    """A :class:`Model` whose params live across HBM / host / disk.

    Forward picks the streamed plan when one is registered for the module
    class; otherwise it materializes everything on the execution device for
    the duration of the call (reference ``cpu_offload`` semantics).
    """

    def __init__(
        self,
        module,
        placed_params,
        device_map,
        execution_device,
        sep: str = "/",
        apply_fn=None,
        extra_state=None,
    ):
        super().__init__(
            module=module, apply_fn=apply_fn, params=placed_params, extra_state=extra_state
        )
        self.device_map = dict(device_map)
        self.execution_device = execution_device
        self._sep = sep

    def __call__(self, *args, **kwargs):
        resolver = ParamResolver(self._params, self.execution_device, sep=self._sep)
        plan = _STREAM_PLANS.get(type(self.module).__name__) if self.module is not None else None
        if plan is not None and not self.extra_state:
            return plan(self.module, resolver, *args, **kwargs)
        full = resolver._materialize(self._params)
        variables = {"params": full}
        if self.extra_state:
            variables.update(self.extra_state)
        try:
            return self.apply_fn(variables, *args, **kwargs)
        finally:
            del full  # evict the transient on-device copy

    def hbm_resident_bytes(self) -> int:
        """Bytes of params permanently resident on device (diagnostics)."""
        total = 0
        for leaf in jax.tree.leaves(self._params):
            if isinstance(leaf, jax.Array):
                total += leaf.nbytes
        return total


def dispatch_model(
    model: Model,
    device_map: Mapping[str, Any],
    offload_dir: Optional[str] = None,
    execution_device=None,
    sep: str = "/",
) -> DispatchedModel:
    """Scatter an in-memory model's params per ``device_map``
    (reference: big_modeling.py:315-521)."""
    flat = flatten_state_dict(model.params, sep=sep)
    device_map = normalize_device_map(device_map)
    placed: dict[str, Any] = {}
    disk_entries: dict[str, np.ndarray] = {}
    for name, arr in flat.items():
        p = placement_for(name, device_map, sep=sep)
        if p == "cpu":
            placed[name] = np.asarray(arr)
        elif p == "disk":
            disk_entries[name] = np.asarray(arr)
        else:
            placed[name] = jax.device_put(arr, p)
    if disk_entries:
        if offload_dir is None:
            raise ValueError("device_map contains 'disk' entries but no offload_dir given")
        offload_state_dict(offload_dir, disk_entries)
        for name, arr in disk_entries.items():
            placed[name] = _DiskHandle(name, offload_dir, arr.shape, arr.dtype)
    if execution_device is None:
        execution_device = default_execution_device(device_map)
    return DispatchedModel(
        model.module,
        unflatten_state_dict(placed, sep=sep),
        device_map,
        execution_device,
        sep=sep,
        apply_fn=None if model.module is not None else model.apply_fn,
        extra_state=model.extra_state,
    )


def cpu_offload(model: Model, execution_device=None) -> DispatchedModel:
    """All params to host RAM; faulted to the chip per forward
    (reference: big_modeling.py:179-231)."""
    top = {k: "cpu" for k in model.params}
    return dispatch_model(model, top, execution_device=execution_device)


def disk_offload(model: Model, offload_dir: str, execution_device=None) -> DispatchedModel:
    """All params to a disk memmap store (reference: big_modeling.py:233-276)."""
    top = {k: "disk" for k in model.params}
    return dispatch_model(model, top, offload_dir=offload_dir, execution_device=execution_device)


class UserCpuOffloadHook:
    """Handle returned by :func:`cpu_offload_with_hook` — ``offload()`` pushes
    the model's params back to host RAM (reference: hooks.py UserCpuOffloadHook
    via big_modeling.py:278-314)."""

    def __init__(self, model: "HookedOffloadModel"):
        self.model = model

    def offload(self):
        self.model._to_host()

    def remove(self):
        self.model._hooked = False


class HookedOffloadModel(Model):
    """Params live on host; the first forward moves them to the chip and they
    STAY resident until ``hook.offload()`` — the pipeline-friendly variant of
    :func:`cpu_offload` (each forward of that one re-faults every group)."""

    def __init__(self, inner: Model, execution_device, prev_hook):
        super().__init__(
            apply_fn=inner.apply_fn, params=inner._params,
            extra_state=inner.extra_state, module=inner.module,
            tp_rules=inner.tp_rules,
        )
        self._exec_device = execution_device
        self._prev_hook = prev_hook
        self._on_device = False
        self._hooked = True
        self._to_host()

    def _host_device(self):
        return jax.local_devices(backend="cpu")[0]

    def _to_host(self):
        self._params = jax.device_put(self._params, self._host_device())
        self._on_device = False

    def __call__(self, *args, **kwargs):
        if self._hooked:
            if self._prev_hook is not None:
                # Chaining: evict the previous pipeline stage before loading
                # this one (the reference's prev_module_hook contract).
                self._prev_hook.offload()
            if not self._on_device:
                self._params = jax.device_put(self._params, self._exec_device)
                self._on_device = True
        return super().__call__(*args, **kwargs)


def cpu_offload_with_hook(
    model: Model, execution_device=None, prev_module_hook: Optional[UserCpuOffloadHook] = None
) -> tuple[Model, UserCpuOffloadHook]:
    """Offload to host, but keep params chip-resident between forwards until
    the returned hook's ``offload()`` runs (reference: big_modeling.py:278-314
    — the diffusers-style pipeline pattern where model_i's load evicts
    model_{i-1} via ``prev_module_hook``)."""
    if execution_device is None:
        execution_device = jax.devices()[0]
    hooked = HookedOffloadModel(model, execution_device, prev_module_hook)
    hook = UserCpuOffloadHook(hooked)
    return hooked, hook


def load_checkpoint_and_dispatch(
    module,
    checkpoint: str,
    *sample_args,
    device_map: Any = "auto",
    max_memory: Optional[dict] = None,
    no_split_modules: Optional[list[str]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    rng=None,
    sep: str = "/",
    **sample_kwargs,
) -> DispatchedModel:
    """Meta-init + auto device map + shard streaming, in one call
    (reference: big_modeling.py:522-662).

    The full model never exists in one memory: shards stream from disk
    straight into their mapped placement.
    """
    abstract = compute_abstract_params(module, *sample_args, rng=rng, **sample_kwargs)
    if device_map in ("auto", "balanced", "balanced_low_0"):
        mm = (
            get_balanced_memory(
                abstract, max_memory, no_split_modules, dtype=dtype,
                low_zero=(device_map == "balanced_low_0"),
            )
            if device_map in ("balanced", "balanced_low_0")
            else get_max_memory(max_memory)
        )
        device_map = infer_auto_device_map(
            abstract, mm, no_split_modules=no_split_modules, dtype=dtype, sep=sep
        )
    elif device_map is None:
        device_map = {"": jax.local_devices()[0]}
    else:
        device_map = normalize_device_map(device_map)
    check_device_map(abstract, device_map, sep=sep)
    placed, _ = load_checkpoint_in_model(
        abstract, checkpoint, device_map=device_map, offload_folder=offload_folder,
        dtype=dtype, sep=sep,
    )
    execution_device = default_execution_device(device_map)
    return DispatchedModel(module, placed, device_map, execution_device, sep=sep)
