"""Big-model inference: load + run models larger than one chip's HBM.

TPU-native redesign of the reference's hook machinery (reference:
big_modeling.py:62-662, hooks.py:242-719). The reference intercepts every
``module.forward`` with ``AlignDevicesHook``s that fault weights in from
CPU/disk and evict them after. Python-per-module hooks would destroy XLA
fusion, so the equivalent here is *layer streaming*:

- params live where the device map put them (HBM / host numpy / disk memmap);
- the forward walks the model's layer stream plan, keeping at most two
  decoder blocks resident: while block *i* computes on the chip, block
  *i+1*'s weights ride the DMA in parallel (``jax.device_put`` is async),
  which is the role of the reference's ``AlignDevicesHook`` prefetch;
- each block reuses ONE jitted computation (identical shapes ⇒ one compile),
  the same trick as the reference's regional compilation
  (utils/other.py:106-177).

Models without a registered stream plan fall back to materialize-per-call
(exactly the reference's ``cpu_offload`` semantics, big_modeling.py:179-231).
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .model import Model
from .utils.modeling import (
    _DiskHandle,
    check_device_map,
    compute_abstract_params,
    default_execution_device,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    normalize_device_map,
    placement_for,
)
from .utils.offload import offload_state_dict
from .utils.other import flatten_state_dict, unflatten_state_dict

__all__ = [
    "init_empty_weights",
    "init_on_device",
    "cpu_offload",
    "cpu_offload_with_hook",
    "disk_offload",
    "dispatch_model",
    "load_checkpoint_and_dispatch",
    "DispatchedModel",
    "UserCpuOffloadHook",
    "register_stream_plan",
    "register_stream_spec",
]


def init_empty_weights(module, *sample_args, rng=None, **sample_kwargs):
    """Abstract-shape init — zero bytes allocated.

    The functional counterpart of the reference's meta-device context manager
    (big_modeling.py:62-178): returns a pytree of ``jax.ShapeDtypeStruct``
    describing ``module.init``'s params.
    """
    return compute_abstract_params(module, *sample_args, rng=rng, **sample_kwargs)


def init_on_device(device):
    """Context manager placing array creation (``module.init`` included) on
    ``device`` — host RAM via ``jax.local_devices(backend="cpu")[0]`` for
    models that must not touch HBM during init (reference:
    big_modeling.py:116-178 ``init_on_device``)."""
    return jax.default_device(device)


# ---------------------------------------------------------------------------
# Param resolver: faults groups in from their placement, with async prefetch
# ---------------------------------------------------------------------------


class ParamResolver:
    """Materialize param subtrees on the execution device on demand.

    ``prefetch`` enqueues the H2D copy immediately and returns; ``take``
    hands the arrays over and evicts them from the cache once consumed —
    together they give the double-buffered pipeline the reference builds
    with hook ``pre_forward``/``post_forward`` pairs (hooks.py:358-431).
    """

    def __init__(self, placed_params, device, sep: str = "/"):
        self.placed = placed_params
        self.device = device
        self.sep = sep
        self._cache: dict[str, Any] = {}
        self._cache_bytes: dict[str, int] = {}
        self.peak_cached_bytes = 0  # high-water mark of concurrently faulted params

    def _subtree(self, prefix: str):
        node = self.placed
        for part in prefix.split(self.sep):
            node = node[part]
        return node

    def _materialize(self, node, layer_index: Optional[int] = None):
        def _leaf(a):
            if isinstance(a, _DiskHandle):
                a = a.load()
            if layer_index is not None:
                a = a[layer_index]
            if isinstance(a, jax.Array) and a.devices() == {self.device}:
                return a
            return jax.device_put(np.asarray(a) if isinstance(a, np.memmap) else a, self.device)

        return jax.tree.map(_leaf, node)

    def _key(self, prefix, layer_index):
        return prefix if layer_index is None else f"{prefix}@{layer_index}"

    @staticmethod
    def _nbytes(tree) -> int:
        return sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(tree)
        )

    def _insert(self, key, value):
        self._cache[key] = value
        self._cache_bytes[key] = self._nbytes(value)
        self.peak_cached_bytes = max(self.peak_cached_bytes, sum(self._cache_bytes.values()))

    def prefetch(self, prefix: str, layer_index: Optional[int] = None):
        key = self._key(prefix, layer_index)
        if key not in self._cache:
            self._insert(key, self._materialize(self._subtree(prefix), layer_index))

    def take(self, prefix: str, layer_index: Optional[int] = None):
        key = self._key(prefix, layer_index)
        if key in self._cache:
            self._cache_bytes.pop(key, None)
            return self._cache.pop(key)
        value = self._materialize(self._subtree(prefix), layer_index)
        self.peak_cached_bytes = max(
            self.peak_cached_bytes, sum(self._cache_bytes.values()) + self._nbytes(value)
        )
        return value

    def peek(self, prefix: str, layer_index: Optional[int] = None):
        """Like take but keeps resident (for groups already living on device)."""
        key = self._key(prefix, layer_index)
        if key not in self._cache:
            self._insert(key, self._materialize(self._subtree(prefix), layer_index))
        return self._cache[key]


# ---------------------------------------------------------------------------
# Generic layer-streaming engine
# ---------------------------------------------------------------------------
#
# The reference's ``AlignDevicesHook`` is architecture-agnostic because torch
# modules expose their submodule tree at runtime (hooks.py:586-719). The
# flax equivalent: every family here factors as
#   embed -> [identical blocks; scanned pytree has the per-layer split] -> head
# so a streamed forward is a *segment list* — cheap declarative specs below —
# walked by ONE engine that double-buffers the layer faults. Families without
# a spec fall back to materialize-per-call with a warning.

_STREAM_PLANS: dict[str, Callable] = {}
_STREAM_SPECS: dict[str, Callable] = {}
_JIT_CACHE: dict[Any, Callable] = {}


def register_stream_plan(module_class_name: str, fn: Callable):
    """Register ``fn(module, resolver, *args) -> output`` as the streamed
    forward for a model family (escape hatch for custom architectures; the
    built-in families use :func:`register_stream_spec`)."""
    _STREAM_PLANS[module_class_name] = fn


def register_stream_spec(module_class_name: str, builder: Callable):
    """Register ``builder(cfg) -> [Seg | LayerSeg, ...]`` for a family."""
    _STREAM_SPECS[module_class_name] = builder


def _jit_for(key, fn):
    if key not in _JIT_CACHE:
        _JIT_CACHE[key] = jax.jit(fn)
    return _JIT_CACHE[key]


class Seg:
    """One faulted group + one jitted fn: ``fn(params_tuple, *carry) -> carry``.

    ``prefixes`` are resolver groups faulted for this segment (passed to the
    fn as a tuple, in order); names in ``keep`` are ``peek``-ed so later
    segments reuse the upload (tied embeddings), the rest are ``take``-n and
    evicted once consumed.
    """

    def __init__(self, name: str, prefixes: list, fn: Callable, keep: tuple = ()):
        self.name = name
        self.prefixes = list(prefixes)
        self.fn = fn
        self.keep = set(keep)


class LayerSeg:
    """A streamed stack of identical blocks.

    The per-layer param split comes from the pytree layout itself: with
    ``scan_layers`` the stacked subtree at ``scan_prefix`` is sliced on its
    leading axis; otherwise ``unscan_fmt.format(i=i)`` names each block's own
    subtree. ``fn(block_params, *carry) -> carry`` runs per layer while the
    next layer's weights ride the DMA (double buffering).
    """

    def __init__(
        self,
        name: str,
        scan_prefix: str,
        unscan_fmt: str,
        n_layers: int,
        fn: Callable,
        offset: int = 0,
    ):
        self.name = name
        self.scan_prefix = scan_prefix
        self.unscan_fmt = unscan_fmt
        self.n_layers = n_layers
        self.fn = fn
        self.offset = offset  # unscanned name index start (T5's block_1..block_{n-1})


def _as_tuple(x):
    return x if isinstance(x, tuple) else (x,)


_warned_fallback: set = set()


def _spec_arity(segments) -> int:
    """Number of model inputs a spec's first segment consumes (its fn takes
    ``(params, *inputs)``)."""
    import inspect

    first = segments[0]
    return len(inspect.signature(first.fn).parameters) - 1


def _leaf_nbytes(leaf) -> int:
    if isinstance(leaf, _DiskHandle):
        return int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
    return getattr(leaf, "nbytes", 0)


def _warn_materialize_fallback(cls_name, params, reason: str):
    """One warning per class: a dispatched model silently materializing
    everything on device was round-2's hidden OOM cliff."""
    if cls_name in _warned_fallback:
        return
    _warned_fallback.add(cls_name)
    total = sum(_leaf_nbytes(leaf) for leaf in jax.tree.leaves(params))
    # Plain stdlib logging: dispatch runs before/without Accelerator() init.
    import logging

    logging.getLogger(__name__).warning(
        "dispatch_model: %s cannot use layer streaming (%s) — the full param "
        "tree (%.2f GB) will be materialized on the execution device for "
        "every forward, defeating offload. register_stream_spec()/"
        "register_stream_plan() add streamed forwards for custom models.",
        cls_name or "<apply_fn model>",
        reason,
        total / 1e9,
    )


def _run_stream_spec(module, resolver: ParamResolver, segments, *inputs):
    cfg = module.config
    carry = tuple(jnp.asarray(a) for a in inputs)
    for seg in segments:
        if isinstance(seg, LayerSeg):
            if getattr(cfg, "scan_layers", False):
                keys = [(seg.scan_prefix, i) for i in range(seg.n_layers)]
            else:
                keys = [
                    (seg.unscan_fmt.format(i=i + seg.offset), None) for i in range(seg.n_layers)
                ]
            if not keys:
                continue
            fn = _jit_for((cfg, seg.name), seg.fn)
            resolver.prefetch(*keys[0])
            for i, (prefix, idx) in enumerate(keys):
                if i + 1 < len(keys):
                    resolver.prefetch(*keys[i + 1])  # DMA overlaps block i's compute
                carry = _as_tuple(fn(resolver.take(prefix, idx), *carry))
        else:
            params = tuple(
                resolver.peek(p) if p in seg.keep else resolver.take(p) for p in seg.prefixes
            )
            carry = _as_tuple(_jit_for((cfg, seg.name), seg.fn)(params, *carry))
    return carry[0]


def _positions_like(input_ids):
    return jnp.broadcast_to(
        jnp.arange(input_ids.shape[-1], dtype=jnp.int32)[None, :], input_ids.shape
    )


def _llama_like_spec(cfg, block_cls, norm_cls):
    """Llama-family decoder (also Mistral/Qwen/Gemma via config, and Mixtral
    with its MoE block): embed [+Gemma scale] -> blocks(x, pos) -> RMSNorm ->
    tied or Dense head."""
    import flax.linen as nn

    embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32)
    block = block_cls(cfg)
    norm = norm_cls()
    tied = cfg.tie_word_embeddings

    def embed_fn(params, input_ids):
        x = embed.apply({"params": params[0]}, input_ids)
        if getattr(cfg, "scale_embeddings", False):
            x = x * jnp.asarray(np.sqrt(cfg.hidden_size), cfg.dtype)
        return x, _positions_like(input_ids)

    def block_fn(p, x, pos):
        return block.apply({"params": p}, x, pos), pos

    if tied:
        def head_fn(params, x, pos):
            x = norm.apply({"params": params[0]}, x)
            return x @ params[1]["embedding"].T.astype(cfg.dtype)

        head = Seg("head", ["model/norm", "model/embed_tokens"], head_fn)
    else:
        def head_fn(params, x, pos):
            x = norm.apply({"params": params[0]}, x)
            return x @ params[1]["kernel"].astype(cfg.dtype)

        head = Seg("head", ["model/norm", "lm_head"], head_fn)

    return [
        Seg("embed", ["model/embed_tokens"], embed_fn, keep=("model/embed_tokens",) if tied else ()),
        LayerSeg("block", "model/layers/block", "model/layers_{i}",
                 cfg.num_hidden_layers, block_fn),
        head,
    ]


def _llama_spec(cfg):
    from .models.llama import LlamaBlock, RMSNorm

    return _llama_like_spec(
        cfg, LlamaBlock,
        lambda: RMSNorm(cfg.rms_norm_eps, getattr(cfg, "rms_norm_plus_one", False)),
    )


def _mixtral_spec(cfg):
    from .models.llama import RMSNorm
    from .models.moe import MixtralBlock

    return _llama_like_spec(cfg, MixtralBlock, lambda: RMSNorm(cfg.rms_norm_eps))


def _opt_spec(cfg):
    """OPT — the reference's OPT-30B big-model-inference workload
    (benchmarks/big_model_inference/README.md) with ≤2 blocks in HBM."""
    import flax.linen as nn

    from .models.opt import OPTBlock

    embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32)
    pos_embed = nn.Embed(
        cfg.max_position_embeddings + cfg.POSITION_OFFSET, cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=jnp.float32,
    )
    block = OPTBlock(cfg)
    ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps)

    def embed_fn(params, input_ids):
        pos = jnp.arange(input_ids.shape[-1]) + cfg.POSITION_OFFSET
        return embed.apply({"params": params[0]}, input_ids) + pos_embed.apply(
            {"params": params[1]}, pos
        )

    def head_fn(params, x):
        x = ln.apply({"params": params[0]}, x)
        return (x @ params[1]["embedding"].T.astype(cfg.dtype)).astype(jnp.float32)

    return [
        Seg("embed", ["model/embed_tokens", "model/embed_positions"], embed_fn,
            keep=("model/embed_tokens",)),
        LayerSeg("block", "model/layers/block", "model/layer_{i}",
                 cfg.num_hidden_layers, lambda p, x: block.apply({"params": p}, x)),
        Seg("head", ["model/final_layer_norm", "model/embed_tokens"], head_fn),
    ]


def _neox_spec(cfg):
    """GPT-NeoX — the reference's flagship 20B offload benchmark family."""
    import flax.linen as nn

    from .models.neox import GPTNeoXBlock

    embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32)
    block = GPTNeoXBlock(cfg)
    ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
    head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32)

    def embed_fn(params, input_ids):
        return embed.apply({"params": params[0]}, input_ids), _positions_like(input_ids)

    def head_fn(params, x, pos):
        x = ln.apply({"params": params[0]}, x)
        return head.apply({"params": params[1]}, x).astype(jnp.float32)

    return [
        Seg("embed", ["gpt_neox/embed_in"], embed_fn),
        LayerSeg("block", "gpt_neox/layers/block", "gpt_neox/layer_{i}",
                 cfg.num_hidden_layers,
                 lambda p, x, pos: (block.apply({"params": p}, x, pos), pos)),
        Seg("head", ["gpt_neox/final_layer_norm", "embed_out"], head_fn),
    ]


def _gpt2_spec(cfg):
    import flax.linen as nn

    from .models.gpt2 import GPT2Block

    wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, param_dtype=jnp.float32)
    wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype, param_dtype=jnp.float32)
    block = GPT2Block(cfg)
    ln = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon)

    def embed_fn(params, input_ids):
        return wte.apply({"params": params[0]}, input_ids) + wpe.apply(
            {"params": params[1]}, jnp.arange(input_ids.shape[-1])
        )

    def head_fn(params, x):
        x = ln.apply({"params": params[0]}, x)
        return (x @ params[1]["embedding"].T.astype(cfg.dtype)).astype(jnp.float32)

    return [
        Seg("embed", ["transformer/wte", "transformer/wpe"], embed_fn,
            keep=("transformer/wte",)),
        LayerSeg("block", "transformer/h/block", "transformer/h_{i}", cfg.n_layer,
                 lambda p, x: block.apply({"params": p}, x)),
        Seg("head", ["transformer/ln_f", "transformer/wte"], head_fn),
    ]


def _t5_spec(cfg):
    """T5 encoder-decoder — the reference's T0pp-11B benchmark family. Both
    stacks stream; block_0 (owner of the shared relative-position bias) is its
    own segment, the remaining bias-reusing layers are the streamed stack."""
    import flax.linen as nn

    from .models.t5 import T5DecoderBlock, T5EncoderBlock, T5LayerNorm

    shared = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32)
    enc_b0 = T5EncoderBlock(cfg, has_relative_bias=True)
    enc_blk = T5EncoderBlock(cfg)
    dec_b0 = T5DecoderBlock(cfg, has_relative_bias=True)
    dec_blk = T5DecoderBlock(cfg)
    final_ln = T5LayerNorm(cfg.layer_norm_epsilon)

    def enc_embed_fn(params, input_ids, decoder_input_ids):
        mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
        return shared.apply({"params": params[0]}, input_ids), mask, decoder_input_ids

    def enc_b0_fn(p, x, mask, dec_ids):
        x, bias = enc_b0.apply({"params": p[0]}, x, mask, None)
        return x, bias, mask, dec_ids

    def enc_blk_fn(p, x, bias, mask, dec_ids):
        x, _ = enc_blk.apply({"params": p}, x, mask, bias)
        return x, bias, mask, dec_ids

    def enc_final_fn(p, x, bias, mask, dec_ids):
        return final_ln.apply({"params": p[0]}, x), mask, dec_ids

    def dec_embed_fn(params, enc, mask, dec_ids):
        return shared.apply({"params": params[0]}, dec_ids), enc, mask

    def dec_b0_fn(p, y, enc, mask):
        y, bias = dec_b0.apply({"params": p[0]}, y, enc, None, mask)
        return y, bias, enc, mask

    def dec_blk_fn(p, y, bias, enc, mask):
        y, _ = dec_blk.apply({"params": p}, y, enc, bias, mask)
        return y, bias, enc, mask

    def head_fn(params, y, bias, enc, mask):
        y = final_ln.apply({"params": params[0]}, y)
        return (y * (cfg.d_model ** -0.5)) @ params[1]["embedding"].T.astype(cfg.dtype)

    return [
        Seg("enc_embed", ["shared"], enc_embed_fn, keep=("shared",)),
        Seg("enc_b0", ["encoder/block_0"], enc_b0_fn),
        LayerSeg("enc_blk", "encoder/layers/block", "encoder/block_{i}",
                 cfg.num_layers - 1, enc_blk_fn, offset=1),
        Seg("enc_final", ["encoder/final_ln"], enc_final_fn),
        Seg("dec_embed", ["shared"], dec_embed_fn, keep=("shared",)),
        Seg("dec_b0", ["decoder/block_0"], dec_b0_fn),
        LayerSeg("dec_blk", "decoder/layers/block", "decoder/block_{i}",
                 cfg.n_dec - 1, dec_blk_fn, offset=1),
        Seg("head", ["decoder/final_ln", "shared"], head_fn),
    ]


def _whisper_spec(cfg):
    import flax.linen as nn
    from functools import partial

    from .models.whisper import WhisperDecoderBlock, WhisperEncoderBlock

    conv = partial(nn.Conv, features=cfg.d_model, kernel_size=(3,), padding=1,
                   dtype=cfg.dtype, param_dtype=jnp.float32)
    conv1, conv2 = conv(), conv(strides=(2,))
    enc_blk = WhisperEncoderBlock(cfg)
    dec_blk = WhisperDecoderBlock(cfg)
    ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
    embed_tok = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32)
    embed_pos = nn.Embed(cfg.max_target_positions, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=jnp.float32)

    def enc_stem_fn(params, feats, dec_ids):
        x = nn.gelu(conv1.apply({"params": params[0]}, feats.astype(cfg.dtype)),
                    approximate=False)
        x = nn.gelu(conv2.apply({"params": params[1]}, x), approximate=False)
        x = x + params[2][None, : x.shape[1]].astype(x.dtype)
        return x, dec_ids

    def enc_ln_fn(p, x, dec_ids):
        return ln.apply({"params": p[0]}, x), dec_ids

    def dec_embed_fn(params, enc, dec_ids):
        y = embed_tok.apply({"params": params[0]}, dec_ids)
        y = y + embed_pos.apply({"params": params[1]}, jnp.arange(dec_ids.shape[-1]))
        return y, enc

    def head_fn(params, y, enc):
        y = ln.apply({"params": params[0]}, y)
        return (y @ params[1]["embedding"].T.astype(cfg.dtype)).astype(jnp.float32)

    return [
        Seg("enc_stem", ["encoder/conv1", "encoder/conv2", "encoder/embed_positions"],
            enc_stem_fn),
        LayerSeg("enc_blk", "encoder/layers/block", "encoder/layer_{i}",
                 cfg.encoder_layers,
                 lambda p, x, dec_ids: (enc_blk.apply({"params": p}, x), dec_ids)),
        Seg("enc_ln", ["encoder/layer_norm"], enc_ln_fn),
        Seg("dec_embed", ["decoder/embed_tokens", "decoder/embed_positions"],
            dec_embed_fn, keep=("decoder/embed_tokens",)),
        LayerSeg("dec_blk", "decoder/layers/block", "decoder/layer_{i}",
                 cfg.decoder_layers,
                 lambda p, y, enc: (dec_blk.apply({"params": p}, y, enc), enc)),
        Seg("head", ["decoder/layer_norm", "decoder/embed_tokens"], head_fn),
    ]


register_stream_spec("LlamaForCausalLM", _llama_spec)
register_stream_spec("MixtralForCausalLM", _mixtral_spec)
register_stream_spec("OPTForCausalLM", _opt_spec)
register_stream_spec("GPTNeoXForCausalLM", _neox_spec)
register_stream_spec("GPT2LMHeadModel", _gpt2_spec)
register_stream_spec("T5ForConditionalGeneration", _t5_spec)
register_stream_spec("WhisperForConditionalGeneration", _whisper_spec)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


class DispatchedModel(Model):
    """A :class:`Model` whose params live across HBM / host / disk.

    Forward picks the streamed plan when one is registered for the module
    class; otherwise it materializes everything on the execution device for
    the duration of the call (reference ``cpu_offload`` semantics).
    """

    def __init__(
        self,
        module,
        placed_params,
        device_map,
        execution_device,
        sep: str = "/",
        apply_fn=None,
        extra_state=None,
    ):
        super().__init__(
            module=module, apply_fn=apply_fn, params=placed_params, extra_state=extra_state
        )
        self.device_map = dict(device_map)
        self.execution_device = execution_device
        self._sep = sep

    def __call__(self, *args, **kwargs):
        resolver = ParamResolver(self._params, self.execution_device, sep=self._sep)
        cls_name = type(self.module).__name__ if self.module is not None else None
        # Sown-output collections ("losses": MoE aux, "intermediates") are
        # produced BY the forward, never consumed — they don't block streaming.
        consumed_state = {
            k: v for k, v in (self.extra_state or {}).items()
            if k not in ("losses", "intermediates")
        }
        reason = None
        if cls_name is None:
            reason = "no flax module (apply_fn-only model)"
        elif consumed_state:
            reason = f"extra_state collections {sorted(consumed_state)} must feed the forward"
        if reason is None:
            spec_builder = _STREAM_SPECS.get(cls_name)
            # Specs cover the module's canonical positional signature only; a
            # call with kwargs or extra optional args (e.g. an explicit T5
            # attention_mask) falls back to the full apply for correctness.
            if spec_builder is not None and not kwargs:
                segments = spec_builder(self.module.config)
                if _spec_arity(segments) == len(args):
                    out = _run_stream_spec(self.module, resolver, segments, *args)
                    self.last_stream_peak_bytes = resolver.peak_cached_bytes
                    return out
                reason = (
                    f"call arity {len(args)} != spec arity {_spec_arity(segments)} "
                    "(optional args need the full signature)"
                )
            elif spec_builder is not None:
                reason = "keyword arguments need the full apply signature"
            plan = _STREAM_PLANS.get(cls_name)
            if plan is not None:
                out = plan(self.module, resolver, *args, **kwargs)
                self.last_stream_peak_bytes = resolver.peak_cached_bytes
                return out
            reason = reason or "no stream plan registered"
        # Fallback: the FULL param tree transiently lands on the execution
        # device — exactly when offload matters most, so say so.
        _warn_materialize_fallback(cls_name, self._params, reason)
        full = resolver._materialize(self._params)
        variables = {"params": full}
        if self.extra_state:
            variables.update(self.extra_state)
        try:
            return self.apply_fn(variables, *args, **kwargs)
        finally:
            del full  # evict the transient on-device copy

    def hbm_resident_bytes(self) -> int:
        """Bytes of params permanently resident on device (diagnostics)."""
        total = 0
        for leaf in jax.tree.leaves(self._params):
            if isinstance(leaf, jax.Array):
                total += leaf.nbytes
        return total


def dispatch_model(
    model: Model,
    device_map: Mapping[str, Any],
    offload_dir: Optional[str] = None,
    execution_device=None,
    sep: str = "/",
) -> DispatchedModel:
    """Scatter an in-memory model's params per ``device_map``
    (reference: big_modeling.py:315-521)."""
    flat = flatten_state_dict(model.params, sep=sep)
    device_map = normalize_device_map(device_map)
    placed: dict[str, Any] = {}
    disk_entries: dict[str, np.ndarray] = {}
    for name, arr in flat.items():
        p = placement_for(name, device_map, sep=sep)
        if p == "cpu":
            placed[name] = np.asarray(arr)
        elif p == "disk":
            disk_entries[name] = np.asarray(arr)
        else:
            placed[name] = jax.device_put(arr, p)
    if disk_entries:
        if offload_dir is None:
            raise ValueError("device_map contains 'disk' entries but no offload_dir given")
        offload_state_dict(offload_dir, disk_entries)
        for name, arr in disk_entries.items():
            placed[name] = _DiskHandle(name, offload_dir, arr.shape, arr.dtype)
    if execution_device is None:
        execution_device = default_execution_device(device_map)
    return DispatchedModel(
        model.module,
        unflatten_state_dict(placed, sep=sep),
        device_map,
        execution_device,
        sep=sep,
        apply_fn=None if model.module is not None else model.apply_fn,
        extra_state=model.extra_state,
    )


def cpu_offload(model: Model, execution_device=None) -> DispatchedModel:
    """All params to host RAM; faulted to the chip per forward
    (reference: big_modeling.py:179-231)."""
    top = {k: "cpu" for k in model.params}
    return dispatch_model(model, top, execution_device=execution_device)


def disk_offload(model: Model, offload_dir: str, execution_device=None) -> DispatchedModel:
    """All params to a disk memmap store (reference: big_modeling.py:233-276)."""
    top = {k: "disk" for k in model.params}
    return dispatch_model(model, top, offload_dir=offload_dir, execution_device=execution_device)


class UserCpuOffloadHook:
    """Handle returned by :func:`cpu_offload_with_hook` — ``offload()`` pushes
    the model's params back to host RAM (reference: hooks.py UserCpuOffloadHook
    via big_modeling.py:278-314)."""

    def __init__(self, model: "HookedOffloadModel"):
        self.model = model

    def offload(self):
        self.model._to_host()

    def remove(self):
        self.model._hooked = False


class HookedOffloadModel(Model):
    """Params live on host; the first forward moves them to the chip and they
    STAY resident until ``hook.offload()`` — the pipeline-friendly variant of
    :func:`cpu_offload` (each forward of that one re-faults every group)."""

    def __init__(self, inner: Model, execution_device, prev_hook):
        super().__init__(
            apply_fn=inner.apply_fn, params=inner._params,
            extra_state=inner.extra_state, module=inner.module,
            tp_rules=inner.tp_rules,
        )
        self._exec_device = execution_device
        self._prev_hook = prev_hook
        self._on_device = False
        self._hooked = True
        self._to_host()

    def _host_device(self):
        return jax.local_devices(backend="cpu")[0]

    def _to_host(self):
        self._params = jax.device_put(self._params, self._host_device())
        self._on_device = False

    def __call__(self, *args, **kwargs):
        if self._hooked:
            if self._prev_hook is not None:
                # Chaining: evict the previous pipeline stage before loading
                # this one (the reference's prev_module_hook contract).
                self._prev_hook.offload()
            if not self._on_device:
                self._params = jax.device_put(self._params, self._exec_device)
                self._on_device = True
        return super().__call__(*args, **kwargs)


def cpu_offload_with_hook(
    model: Model, execution_device=None, prev_module_hook: Optional[UserCpuOffloadHook] = None
) -> tuple[Model, UserCpuOffloadHook]:
    """Offload to host, but keep params chip-resident between forwards until
    the returned hook's ``offload()`` runs (reference: big_modeling.py:278-314
    — the diffusers-style pipeline pattern where model_i's load evicts
    model_{i-1} via ``prev_module_hook``)."""
    if execution_device is None:
        execution_device = jax.devices()[0]
    hooked = HookedOffloadModel(model, execution_device, prev_module_hook)
    hook = UserCpuOffloadHook(hooked)
    return hooked, hook


def load_checkpoint_and_dispatch(
    module,
    checkpoint: str,
    *sample_args,
    device_map: Any = "auto",
    max_memory: Optional[dict] = None,
    no_split_modules: Optional[list[str]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    rng=None,
    sep: str = "/",
    **sample_kwargs,
) -> DispatchedModel:
    """Meta-init + auto device map + shard streaming, in one call
    (reference: big_modeling.py:522-662).

    The full model never exists in one memory: shards stream from disk
    straight into their mapped placement.
    """
    abstract = compute_abstract_params(module, *sample_args, rng=rng, **sample_kwargs)
    if device_map in ("auto", "balanced", "balanced_low_0"):
        mm = (
            get_balanced_memory(
                abstract, max_memory, no_split_modules, dtype=dtype,
                low_zero=(device_map == "balanced_low_0"),
            )
            if device_map in ("balanced", "balanced_low_0")
            else get_max_memory(max_memory)
        )
        device_map = infer_auto_device_map(
            abstract, mm, no_split_modules=no_split_modules, dtype=dtype, sep=sep
        )
    elif device_map is None:
        device_map = {"": jax.local_devices()[0]}
    else:
        device_map = normalize_device_map(device_map)
    check_device_map(abstract, device_map, sep=sep)
    placed, _ = load_checkpoint_in_model(
        abstract, checkpoint, device_map=device_map, offload_folder=offload_folder,
        dtype=dtype, sep=sep,
    )
    execution_device = default_execution_device(device_map)
    return DispatchedModel(module, placed, device_map, execution_device, sep=sep)
