"""Deprecated shim kept for reference-API parity
(reference: memory_utils.py:18-22 — same warning, same re-export)."""

import warnings

from .utils.memory import *  # noqa: F401,F403

warnings.warn(
    "memory_utils has moved to accelerate_tpu.utils.memory; this alias will "
    "be removed in a future release.",
    FutureWarning,
)
