"""Long-context generation over the ``cp`` mesh axis — flash-decoding on ICI.

The reference's context parallelism is training-only
(reference: accelerator.py:1658-1671 ``_prepare_cp``; its `.generate()` path
never shards a sequence). Here long prompts generate too:

- **Prefill** runs the prompt with sequence sharded over ``cp`` through ring
  attention (parallel/cp.py) — each chip holds S/cp of every layer's K/V, so
  a prompt ``cp×`` longer than one chip's HBM fits. The per-layer K/V chunks
  are kept, sequence-sharded, as the **prefix cache**.
- **Decode** is flash-decoding distributed over the ring: each step's query
  computes online-softmax partials (acc, m, l) against the *local* prefix
  shard; the cross-chip max/sum/weighted-value reductions are placed by
  GSPMD from the shardings — three small collectives per layer, no gathered
  cache, HBM stays O(S/cp) per chip. Newly generated tokens land in a small
  replicated **tail cache** (they are recent and tiny), merged with the
  prefix partials by the standard online-softmax combination.

Supported: the Llama plan family (Llama/Mistral/Qwen2/Gemma checkpoints).
The single-chip analog is ``generation.generate``; token-for-token greedy
parity between the two is pinned by tests/test_cp_generation.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .models.llama import rms_norm, rotary_embedding
from .ops.flash_attention import attention_stats
from .generation import (
    _embed_tokens,
    _mlp,
    _norm_w,
    _out_proj,
    _qkv_proj,
    sample_logits,
)

_CP_LOOP_CACHE: dict = {}


def clear_cp_generation_cache():
    _CP_LOOP_CACHE.clear()


def _dp_axes(mesh) -> tuple:
    return tuple(
        a for a in ("dp_replicate", "dp_shard")
        if a in mesh.shape and mesh.shape[a] > 1
    )


def _manual_cp(mesh) -> bool:
    """True when the ``cp`` axis is real and cross-shard ops must be issued
    as manual shard_map collectives. GSPMD's auto-partitioned gather/slice/
    reduce over a cp-sharded dim miscompiles on the CPU SPMD backend when the
    mesh has other nontrivial axes (partial results are re-summed over
    uninvolved axes, scaling values by that axis size), so everything that
    communicates over cp goes through an explicit shard_map body instead."""
    return mesh.shape.get("cp", 1) > 1


def _embed_sharded(cfg, embed, input_ids, mesh, batch_axes):
    """Embedding lookup with ids sequence-sharded over ``cp``: the table is
    replicated, each chip gathers its own id chunk locally."""
    from .utils.environment import shard_map_compat

    b_ax = batch_axes if batch_axes else None
    return shard_map_compat(
        lambda tbl, idc: _embed_tokens(cfg, tbl, idc),
        mesh=mesh,
        in_specs=(P(None, None), P(b_ax, "cp")),
        out_specs=P(b_ax, "cp", None),
        check_vma=False,
    )(embed, input_ids)


def _gather_seq(ids, mesh, batch_axes):
    """(B, S) cp-sharded -> replicated, via a manual tiled all_gather (the
    output concat would otherwise auto-reshard over cp)."""
    from .utils.environment import shard_map_compat

    b_ax = batch_axes if batch_axes else None

    def body(i_c):
        return jax.lax.all_gather(i_c, "cp", axis=1, tiled=True)

    return shard_map_compat(
        body, mesh=mesh, in_specs=(P(b_ax, "cp"),),
        out_specs=P(b_ax, None), check_vma=False,
    )(ids)


def _last_position(x, mesh, batch_axes):
    """(B, S, E) with S cp-sharded -> (B, E) at the last global position,
    replicated. The final chunk lives on the last cp shard; a tiny all_gather
    of each shard's local last row keeps the extraction manual."""
    from .utils.environment import shard_map_compat

    b_ax = batch_axes if batch_axes else None

    def body(x_c):
        return jax.lax.all_gather(x_c[:, -1], "cp")[-1]

    return shard_map_compat(
        body, mesh=mesh, in_specs=(P(b_ax, "cp", None),),
        out_specs=P(b_ax, None), check_vma=False,
    )(x)


def _prefix_stats_sharded(q, pk, pv, mesh, batch_axes):
    """Flash-decoding partials against the cp-sharded prefix: local stats per
    shard, then the exact online-softmax merge over cp as manual pmax/psum
    (disjoint keysets, same combination as :func:`_merge_stats`)."""
    from .utils.environment import shard_map_compat

    b_ax = batch_axes if batch_axes else None

    def body(q_c, k_c, v_c):
        acc, m, l = attention_stats(q_c, k_c, v_c, causal=False)
        m_g = jax.lax.pmax(m, "cp")
        w = jnp.exp(m - m_g)
        l_g = jax.lax.psum(l * w, "cp")
        acc_g = jax.lax.psum(acc * w[..., None], "cp")
        return acc_g, m_g, l_g

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(b_ax, None, None, None),
            P(b_ax, "cp", None, None),
            P(b_ax, "cp", None, None),
        ),
        out_specs=(
            P(b_ax, None, None, None),
            P(b_ax, None, None),
            P(b_ax, None, None),
        ),
        check_vma=False,
    )(q, pk, pv)


def _merge_stats(parts):
    """Exact combination of disjoint-keyset online-softmax partials."""
    m = parts[0][1]
    for _, mi, _ in parts[1:]:
        m = jnp.maximum(m, mi)
    l = sum(li * jnp.exp(mi - m) for _, mi, li in parts)
    acc = sum(ai * jnp.exp(mi - m)[..., None] for ai, mi, _ in parts)
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, H, Sq, D)
    return out.transpose(0, 2, 1, 3)  # (B, Sq, H, D)


def _unpack(cfg, params):
    model_p = params["model"] if "model" in params else params
    stacked = model_p["layers"]["block"]
    embed = model_p["embed_tokens"]["embedding"]
    final_norm = model_p["norm"]["weight"]
    head = embed.T if cfg.tie_word_embeddings else params["lm_head"]["kernel"]
    return stacked, embed, final_norm, head


def _prefill(cfg, params, input_ids, mesh, batch_axes=()):
    """Prompt forward with seq sharded over cp; ring attention per layer.
    Returns (last-token logits (B,V) fp32, prefix_k, prefix_v) with the
    prefix caches (L,B,S,Hkv,D) sequence-sharded over ``cp``."""
    from .parallel.cp import ring_attention

    stacked, embed, final_norm, head = _unpack(cfg, params)
    b, s = input_ids.shape
    if _manual_cp(mesh):
        x = _embed_sharded(cfg, embed, input_ids, mesh, batch_axes)
    else:
        x = _embed_tokens(cfg, embed, input_ids)
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta, x.dtype)
    eps = cfg.rms_norm_eps

    def one_layer(h, p):
        hn = rms_norm(h, _norm_w(cfg, p["input_layernorm"]["weight"], h), eps)
        q, k_new, v_new = _qkv_proj(p["self_attn"], hn, cos, sin)
        out = ring_attention(q, k_new, v_new, causal=True, mesh=mesh, batch_axes=batch_axes)
        h = h + _out_proj(out.astype(h.dtype), p["self_attn"]["o_proj"]["kernel"])
        hn = rms_norm(h, _norm_w(cfg, p["post_attention_layernorm"]["weight"], h), eps)
        h = h + _mlp(cfg, p["mlp"], hn)
        return h, (k_new.astype(cfg.dtype), v_new.astype(cfg.dtype))

    x, (pk, pv) = jax.lax.scan(one_layer, x, stacked)
    x = rms_norm(x, _norm_w(cfg, final_norm, x), eps)
    if _manual_cp(mesh):
        last = _last_position(x, mesh, batch_axes)
    else:
        last = x[:, -1]
    logits = last @ head.astype(cfg.dtype)
    return logits.astype(jnp.float32), pk, pv


def _decode_loop(cfg, params, first_token, prefix_k, prefix_v, max_new_tokens,
                 *, rng, temperature, top_k, top_p, eos_token_id, pad_token_id,
                 prompt_len, finished0=None, mesh=None, batch_axes=()):
    """lax.scan over decode steps. Tail caches are replicated (N is small);
    the prefix stays sequence-sharded — attention merges per-chip partials."""
    stacked, embed, final_norm, head = _unpack(cfg, params)
    b = first_token.shape[0]
    n_layers, _, _, hkv, d = prefix_k.shape
    n_tail = max_new_tokens
    eps = cfg.rms_norm_eps

    tail_k = jnp.zeros((n_layers, b, n_tail, hkv, d), cfg.dtype)
    tail_v = jnp.zeros_like(tail_k)

    def forward_one(token, t, tk_all, tv_all):
        x = _embed_tokens(cfg, embed, token[:, None])
        pos = jnp.broadcast_to(
            jnp.asarray(prompt_len + t, jnp.int32)[None, None], (b, 1)
        )
        cos, sin = rotary_embedding(pos, cfg.head_dim, cfg.rope_theta, x.dtype)

        def one_layer(h, layer):
            p, pk, pv, tk, tv = layer
            hn = rms_norm(h, _norm_w(cfg, p["input_layernorm"]["weight"], h), eps)
            q, k_new, v_new = _qkv_proj(p["self_attn"], hn, cos, sin)
            tk = jax.lax.dynamic_update_slice(tk, k_new.astype(tk.dtype), (0, t, 0, 0))
            tv = jax.lax.dynamic_update_slice(tv, v_new.astype(tv.dtype), (0, t, 0, 0))
            # Flash-decoding: partials against the LOCAL prefix shard, merged
            # over cp with manual pmax/psum collectives, plus partials
            # against the replicated tail.
            if mesh is not None and _manual_cp(mesh):
                stats_prefix = _prefix_stats_sharded(q, pk, pv, mesh, batch_axes)
            else:
                stats_prefix = attention_stats(q, pk, pv, causal=False)
            stats_tail = attention_stats(q, tk, tv, causal=False, kv_valid_len=t + 1)
            out = _merge_stats([stats_prefix, stats_tail])
            h = h + _out_proj(out.astype(h.dtype), p["self_attn"]["o_proj"]["kernel"])
            hn = rms_norm(h, _norm_w(cfg, p["post_attention_layernorm"]["weight"], h), eps)
            h = h + _mlp(cfg, p["mlp"], hn)
            return h, (tk, tv)

        x, (tk_all, tv_all) = jax.lax.scan(
            one_layer, x, (stacked, prefix_k, prefix_v, tk_all, tv_all)
        )
        x = rms_norm(x, _norm_w(cfg, final_norm, x), eps)
        logits = (x[:, -1] @ head.astype(cfg.dtype)).astype(jnp.float32)
        return logits, tk_all, tv_all

    def pick(logits, key):
        if temperature is None or temperature <= 0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )

    def step(carry, t):
        token, tk_all, tv_all, finished, key = carry
        key, sub = jax.random.split(key)
        logits, tk_all, tv_all = forward_one(token, t, tk_all, tv_all)
        nxt = pick(logits, sub)
        if eos_token_id is not None:
            nxt = jnp.where(finished, pad_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        return (nxt, tk_all, tv_all, finished, key), nxt

    finished = finished0 if finished0 is not None else jnp.zeros((b,), bool)
    key = rng if rng is not None else jax.random.key(0)
    _, toks = jax.lax.scan(
        step,
        (first_token, tail_k, tail_v, finished, key),
        jnp.arange(max_new_tokens, dtype=jnp.int32),
    )
    return toks.T  # (B, N)


def cp_generate(
    model,
    input_ids,
    max_new_tokens: int,
    *,
    temperature: Optional[float] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: Optional[int] = None,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> jax.Array:
    """Generate with the prompt sequence sharded over the ``cp`` mesh axis.

    ``input_ids`` (B, S): S must divide by the cp degree. Returns
    (B, S + max_new_tokens) like :func:`generation.generate`. Greedy output
    is token-identical to the single-chip path (pinned by tests).
    """
    from .state import AcceleratorState

    cfg = model.module.config
    params = model.params
    if mesh is None:
        mesh = AcceleratorState().mesh
    cp = mesh.shape.get("cp", 1)
    b, s = input_ids.shape
    if max_new_tokens <= 0:
        # (B, S + 0): the documented contract — matches generation.generate,
        # whose lax.scan over arange(0) appends nothing.
        return jnp.asarray(input_ids, jnp.int32)
    if s % cp != 0:
        raise ValueError(f"prompt length {s} must divide by cp={cp}")
    if not cfg.scan_layers:
        raise ValueError("cp_generate requires scan_layers=True (stacked blocks)")
    max_pos = getattr(cfg, "max_position_embeddings", None)
    if max_pos is not None and s + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings ({max_pos})"
        )
    if pad_token_id is None:
        pad_token_id = eos_token_id if eos_token_id is not None else 0

    dp = _dp_axes(mesh)
    dp_total = int(np.prod([mesh.shape[a] for a in dp])) if dp else 1
    if b % dp_total != 0:
        dp = ()  # small generation batches replicate over dp
    ids_sharding = NamedSharding(mesh, P(dp if dp else None, "cp"))
    prefix_spec = P(None, dp if dp else None, "cp", None, None)

    key = (
        id(model.module), cfg, b, s, int(max_new_tokens), temperature, top_k,
        top_p, eos_token_id, pad_token_id, mesh,
    )
    fn = _CP_LOOP_CACHE.get(key)
    if fn is None:

        def run(params, ids, rng_key):
            logits0, pk, pv = _prefill(cfg, params, ids, mesh, batch_axes=dp)
            pk = jax.lax.with_sharding_constraint(pk, NamedSharding(mesh, prefix_spec))
            pv = jax.lax.with_sharding_constraint(pv, NamedSharding(mesh, prefix_spec))
            if temperature is None or temperature <= 0:
                first = jnp.argmax(logits0, axis=-1).astype(jnp.int32)
            else:
                rng_key, sub = jax.random.split(rng_key)
                first = sample_logits(
                    logits0, sub, temperature=temperature, top_k=top_k, top_p=top_p
                )
            finished0 = jnp.zeros((b,), bool)
            if eos_token_id is not None:
                finished0 = first == eos_token_id
            rest = _decode_loop(
                cfg, params, first, pk, pv, max_new_tokens - 1,
                rng=rng_key, temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id, pad_token_id=pad_token_id,
                prompt_len=s,  # `first` sits at position s; step t decodes s+t
                finished0=finished0, mesh=mesh, batch_axes=dp,
            ) if max_new_tokens > 1 else jnp.zeros((b, 0), jnp.int32)
            ids_full = _gather_seq(ids, mesh, dp) if _manual_cp(mesh) else ids
            out = jnp.concatenate([ids_full, first[:, None], rest], axis=1)
            return out

        fn = _CP_LOOP_CACHE[key] = jax.jit(run)
        while len(_CP_LOOP_CACHE) > 32:  # FIFO cap, same rationale as
            _CP_LOOP_CACHE.pop(next(iter(_CP_LOOP_CACHE)))  # _GEN_LOOP_CACHE

    ids = jax.device_put(jnp.asarray(input_ids, jnp.int32), ids_sharding)
    rng_key = rng if rng is not None else jax.random.key(0)
    return fn(params, ids, rng_key)
