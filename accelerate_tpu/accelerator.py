"""The Accelerator façade (layer L5) — TPU-native.

Re-design of the reference's 4359-line ``accelerator.py``. The reference
rewires torch objects in place and intercepts the imperative loop
(``backward``/``step``/``zero_grad``). Here the same *user-visible flow* is
kept, but under it everything is one canonical sharded
:class:`~accelerate_tpu.train_state.TrainState` and jit-compiled functions over
a GSPMD mesh:

- ``prepare(model, tx, dataloader, schedule)`` plans NamedShardings for every
  param/optimizer leaf from ParallelismConfig + FSDP plugin + TP rules, puts
  the state on the mesh, and wraps the dataloader to emit global batch arrays.
- Imperative surface: ``backward(loss_fn, batch)`` runs a jitted
  value-and-grad (grads come out DP-mean'd by GSPMD — the reference needs a
  DDP reducer, reference: accelerator.py:1892-1896); ``optimizer.step()``
  applies them through a jitted update on accumulation boundaries.
- Fused surface (the fast path): ``prepare_train_step(loss_fn)`` returns ONE
  jitted step with grad-accum, clipping, precision policy and donation folded
  in — the idiomatic JAX shape the reference cannot express.

Gradient accumulation, ``accumulate()``, ``clip_grad_norm_``,
``gather_for_metrics``, trigger sync, checkpointing and tracking keep the
reference's semantics (reference: accelerator.py:1131-1381, 2818-2999,
3068-3140, 3584-3748).
"""

from __future__ import annotations

import contextlib
import functools
import math
import os
import time
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .data_loader import BaseDataLoader, prepare_data_loader, skip_first_batches
from .logging import get_logger
from .model import Model
from .optimizer import AcceleratedOptimizer
from .parallelism_config import ParallelismConfig
from .parallel.sharding import (
    batch_partition_spec,
    infer_opt_state_sharding,
    plan_parameter_sharding,
    replicated,
)
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, DistributedType, GradientState, PartialState
from .tracking import GeneralTracker, filter_trackers
from .train_state import DynamicLossScale, TrainState, grads_all_finite
from .utils import (
    DataLoaderConfiguration,
    DistributedOperationException,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    JitConfig,
    MixedPrecisionPolicy,
    ProjectConfiguration,
    convert_bytes,
    extract_model_from_parallel,
    flatten_state_dict,
    gather,
    gather_object,
    pad_across_processes,
    recursively_apply,
    to_global_host,
    reduce,
    save_sharded_safetensors,
    set_seed,
)
from .utils.dataclasses import (
    AutoPlanKwargs,
    CompileKwargs,
    DisaggConfig,
    DistributedDataParallelKwargs,
    ElasticKwargs,
    FaultToleranceKwargs,
    KwargsHandler,
    ProfileKwargs,
    ServingConfig,
    TelemetryKwargs,
)

logger = get_logger(__name__)

try:
    import optax
except ImportError:  # pragma: no cover
    optax = None


def _is_optax_tx(obj) -> bool:
    return (
        hasattr(obj, "init")
        and hasattr(obj, "update")
        and not isinstance(obj, (Model, BaseDataLoader))
        and not hasattr(obj, "apply_fn")
    )


def _is_dataloader_like(obj) -> bool:
    if isinstance(obj, BaseDataLoader):
        return True
    return hasattr(obj, "dataset") or (
        hasattr(obj, "__iter__") and hasattr(obj, "batch_size")
    )


def _is_schedule(obj) -> bool:
    return callable(obj) and not _is_optax_tx(obj) and not isinstance(obj, Model) and not _is_dataloader_like(obj)


def _microbatch_split(batch, num_accum: int, what: str = "Batch"):
    """(B, ...) → (accum, B/accum, ...) without moving data across devices:
    the batch dim stays dp-sharded on the first reshaped dim (each device's
    contiguous block is a multiple of accum), the transpose is a layout
    change. Shared by the normal and comm-hook train steps — their
    accumulation semantics must never diverge."""

    def _split(x):
        b = x.shape[0]
        if b % num_accum != 0:
            raise ValueError(
                f"{what} dim {b} not divisible by gradient "
                f"accumulation steps {num_accum}."
            )
        x = x.reshape(b // num_accum, num_accum, *x.shape[1:])
        return jnp.swapaxes(x, 0, 1)

    return jax.tree.map(_split, batch)


class _HookHandle:
    """Removable registration handle (torch's RemovableHandle contract)."""

    def __init__(self, registry: list, hook):
        self._registry = registry
        self._hook = hook

    def remove(self):
        if self._hook in self._registry:
            self._registry.remove(self._hook)


class Accelerator:
    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        log_with=None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: Optional[list[KwargsHandler]] = None,
        parallelism_config: "Optional[ParallelismConfig | str]" = None,
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        deepspeed_plugin=None,
        jit_config: Optional[JitConfig] = None,
        rng_types: Optional[list[str]] = None,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        self._ds_gradient_clipping = None
        if deepspeed_plugin is not None:
            if fsdp_plugin is None:
                # ZeRO stages are sharding specs here (SURVEY.md §2.9).
                fsdp_plugin = deepspeed_plugin.to_fsdp_plugin()
            # A migrated ds_config's accumulation/clipping apply like the DS
            # engine applied them (from_ds_json) unless overridden here.
            if (
                gradient_accumulation_steps == 1
                and gradient_accumulation_plugin is None
                and deepspeed_plugin.gradient_accumulation_steps > 1
            ):
                gradient_accumulation_steps = deepspeed_plugin.gradient_accumulation_steps
            self._ds_gradient_clipping = deepspeed_plugin.gradient_clipping
        if fsdp_plugin is None and os.environ.get("ACCELERATE_USE_FSDP", "false").lower() == "true":
            fsdp_plugin = FullyShardedDataParallelPlugin()
        self.fsdp_plugin = fsdp_plugin

        # kwargs handlers (reference: accelerator.py:415-452)
        self.scaler_handler = None
        self.profile_handler = None
        self.fp8_recipe_handler = None
        self.ddp_handler = None
        self.telemetry_handler = None
        self.compile_handler = None
        self.fault_tolerance_handler = None
        self.auto_plan_handler = None
        self.elastic_handler = None
        # Serving config (serving.py): stored only — no serving code runs on
        # the training path; build_serving_engine constructs the engine.
        self.serving_config = None
        # Disaggregated-serving config (disagg.py): stored only; with one
        # present, build_serving_engine returns the two-mesh router.
        self.disagg_config = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe_handler = handler
            elif isinstance(handler, DistributedDataParallelKwargs):
                self.ddp_handler = handler
            elif isinstance(handler, TelemetryKwargs):
                self.telemetry_handler = handler
            elif isinstance(handler, CompileKwargs):
                self.compile_handler = handler
            elif isinstance(handler, FaultToleranceKwargs):
                self.fault_tolerance_handler = handler
            elif isinstance(handler, ServingConfig):
                self.serving_config = handler
            elif isinstance(handler, DisaggConfig):
                self.disagg_config = handler
            elif isinstance(handler, AutoPlanKwargs):
                self.auto_plan_handler = handler
            elif isinstance(handler, ElasticKwargs):
                self.elastic_handler = handler

        if gradient_accumulation_plugin is None:
            ga_steps = int(
                os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS", gradient_accumulation_steps)
            )
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=ga_steps)
        self.gradient_state = GradientState(gradient_accumulation_plugin=gradient_accumulation_plugin)

        # Auto-parallelism (planner.py): parallelism_config="auto" — or an
        # AutoPlanKwargs handler — defers the layout choice to the planner at
        # prepare() time (the first call that sees a model). The mesh stays
        # unbuilt until then; an explicit ParallelismConfig is unchanged.
        if isinstance(parallelism_config, str):
            if parallelism_config != "auto":
                raise ValueError(
                    f"parallelism_config accepts a ParallelismConfig or the "
                    f"string 'auto', got {parallelism_config!r}"
                )
            parallelism_config = None
            if self.auto_plan_handler is None:
                self.auto_plan_handler = AutoPlanKwargs()
        self._auto_plan_pending = (
            self.auto_plan_handler is not None and self.auto_plan_handler.enabled
        )
        self.active_plan = None       # resolved ParallelPlan (auto mode only)
        self.active_plan_meta = None  # {"path": ..., "from_cache": ...}

        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            parallelism_config=parallelism_config,
        )
        self.jit_config = jit_config or JitConfig.from_env()
        if self.jit_config.persistent_cache_dir:
            # Validated (created; warning_once when unusable) instead of the
            # old bare passthrough of a possibly-bad path to jax.config.
            from .compile_manager import configure_persistent_cache

            self.jit_config.persistent_cache_dir = configure_persistent_cache(self.jit_config)

        self._mp_policy = MixedPrecisionPolicy.from_mixed_precision(self.state.mixed_precision)
        self.device_placement = device_placement
        self.split_batches = split_batches
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.dataloader_config = dataloader_config or DataLoaderConfiguration(
            split_batches=split_batches
        )
        self.rng_types = rng_types

        # Registries (reference: accelerator.py:617-622)
        self._models: list[Model] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[BaseDataLoader] = []
        self._custom_objects: list = []
        self._save_state_pre_hooks: list[Callable] = []
        self._load_state_pre_hooks: list[Callable] = []

        # One TrainState per prepared model ("slot"); slot 0 is the primary
        # and backs the legacy single-model surface (_train_state property,
        # imperative backward, LocalSGD). Multi-model training — GANs,
        # distillation, RLHF — prepares several models and steps each through
        # prepare_train_step(loss_fn, model=...) (reference trains multiple
        # models per Accelerator natively since torch params live on modules).
        self._train_states: list[TrainState] = []
        self._slot_meta: list[dict] = []  # per-slot sharding plans
        self._state_shardings = None
        self._grad_shardings = None  # ZeRO-2 reduce-scatter constraint
        self._opt_offload = None     # (device, host) opt shardings under cpu_offload
        self._scheduler: Optional[AcceleratedScheduler] = None
        self._max_grad_norm: Optional[float] = None
        self._grad_fn_cache: dict = {}
        self._apply_jit = None
        self._gradnorm_jit = None
        self.step = 0
        self.flag_tensor = None

        # Tracking (reference: accelerator.py:3271-3408)
        self.log_with = filter_trackers(log_with, self.project_configuration.logging_dir)
        self.trackers: list[GeneralTracker] = []

        # Step-level telemetry (telemetry.py): off unless a TelemetryKwargs
        # handler was passed — every hot-path hook is then a None check.
        self.telemetry = None
        if self.telemetry_handler is not None and self.telemetry_handler.enabled:
            from .telemetry import TelemetryRecorder

            self.telemetry = TelemetryRecorder(self, self.telemetry_handler)

        # Compile manager (compile_manager.py): shape bucketing, AOT warmup
        # and persistent-cache control. Same contract as telemetry — off
        # unless a CompileKwargs handler was passed, then every hook site is
        # a None check.
        self.compile_manager = None
        if self.compile_handler is not None and self.compile_handler.enabled:
            from .compile_manager import CompileManager

            self.compile_manager = CompileManager(self, self.compile_handler)

        # Fault tolerance (fault_tolerance.py): atomic verified checkpoints,
        # preemption auto-save, save retry, divergence sentinel. Same
        # contract as telemetry — off unless a FaultToleranceKwargs handler
        # was passed, then every hook site is a None check and the
        # checkpoint byte layout is unchanged.
        self.fault_tolerance = None
        if self.fault_tolerance_handler is not None and self.fault_tolerance_handler.enabled:
            from .fault_tolerance import FaultToleranceManager

            self.fault_tolerance = FaultToleranceManager(self, self.fault_tolerance_handler)

        # Elastic resharding (resharding.py): restore a checkpoint written on
        # a different topology through a planned redistribution schedule, and
        # hot-swap layouts mid-run via migrate_plan(). Same contract as the
        # managers above — off unless an ElasticKwargs handler was passed,
        # then every hook site is a None check; without it a topology
        # mismatch raises TopologyMismatchError instead of resharding.
        self.elastic = None
        if self.elastic_handler is not None and self.elastic_handler.enabled:
            from .resharding import ElasticManager

            self.elastic = ElasticManager(self, self.elastic_handler)

    # ------------------------------------------------------------------
    # Introspection properties (reference: accelerator.py:640-780)
    # ------------------------------------------------------------------

    @property
    def distributed_type(self) -> DistributedType:
        return self.state.distributed_type

    @property
    def use_distributed(self) -> bool:
        return self.state.use_distributed

    @property
    def num_processes(self) -> int:
        return self.state.num_processes

    @property
    def process_index(self) -> int:
        return self.state.process_index

    @property
    def local_process_index(self) -> int:
        return self.state.local_process_index

    @property
    def device(self):
        return self.state.device

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def parallelism_config(self) -> Optional[ParallelismConfig]:
        return self.state.parallelism_config

    @property
    def is_main_process(self) -> bool:
        return self.state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.state.is_last_process

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def fp8_dot_general(self):
        """Recipe-configured fp8 dot_general for custom modules (None unless
        mixed_precision="fp8"); model configs with an ``fp8`` flag wire this
        in automatically (ops/fp8.py)."""
        if self.state.mixed_precision != "fp8":
            return None
        from .ops.fp8 import fp8_dot_general

        # amax_history_len / amax_compute_algo are delayed-scaling knobs the
        # reference needs on GPU; current scaling fuses into the producer under
        # XLA, so only format and eval policy carry over (ops/fp8.py).
        recipe = self.fp8_recipe_handler
        return fp8_dot_general(
            recipe.fp8_format if recipe else "HYBRID",
            use_during_eval=recipe.use_during_eval if recipe else False,
            native=recipe.native_dots if recipe else None,
        )

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value: int):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    # -- mesh-axis rank properties (reference: accelerator.py ParallelismConfig
    # rank accessors; here a rank is the device's coordinate on the mesh axis,
    # derived from process_index over the process-contiguous axis order) -----

    def _axis_rank(self, axis: str) -> int:
        mesh = self.mesh
        if mesh is None or axis not in mesh.shape or mesh.shape[axis] == 1:
            return 0
        # Read this process's coordinate off the mesh itself: device order may
        # be ICI-optimized (mesh_utils.create_device_mesh), so arithmetic on
        # process_index would lie on multi-host meshes.
        dev = jax.local_devices()[0]
        coords = np.argwhere(mesh.devices == dev)
        if coords.size == 0:
            return 0
        axis_idx = list(mesh.shape.keys()).index(axis)
        return int(coords[0][axis_idx])

    @property
    def data_parallel_rank(self) -> int:
        return self._axis_rank("dp_replicate")

    @property
    def data_parallel_shard_rank(self) -> int:
        return self._axis_rank("dp_shard")

    @property
    def context_parallel_rank(self) -> int:
        return self._axis_rank("cp")

    @property
    def tensor_parallel_rank(self) -> int:
        return self._axis_rank("tp")

    @property
    def pipeline_parallel_rank(self) -> int:
        return self._axis_rank("pp")

    @property
    def optimizer_step_was_skipped(self) -> bool:
        """True if the last optimizer step was skipped (fp16 overflow) —
        reference: accelerator.py GradScaler bookkeeping; here the fused step
        freezes params on non-finite grads and the wrapped optimizer records
        it."""
        return any(opt.step_was_skipped for opt in self._optimizers)

    # -- dataloader-config passthroughs (reference exposes these directly;
    # split_batches is already a ctor-set attribute) ---

    @property
    def dispatch_batches(self):
        return self.dataloader_config.dispatch_batches

    @property
    def even_batches(self) -> bool:
        return self.dataloader_config.even_batches

    @property
    def use_seedable_sampler(self) -> bool:
        return self.dataloader_config.use_seedable_sampler

    @property
    def non_blocking(self) -> bool:
        """Parity shim: device transfers are async by construction in JAX."""
        return True

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def save_iteration(self):
        return self.project_configuration.iteration

    @property
    def train_state(self) -> Optional[TrainState]:
        return self._train_state

    @train_state.setter
    def train_state(self, value: TrainState):
        self._train_state = value

    @property
    def _train_state(self) -> Optional[TrainState]:
        """Primary (slot-0) train state; None before prepare()."""
        states = getattr(self, "_train_states", None)
        return states[0] if states else None

    @_train_state.setter
    def _train_state(self, value: Optional[TrainState]):
        if value is None:
            self._train_states = []
            self._slot_meta = []
        elif getattr(self, "_train_states", None):
            self._train_states[0] = value
        else:
            self._train_states = [value]

    @property
    def state_shardings(self):
        return self._state_shardings

    # ------------------------------------------------------------------
    # Process-control passthrough (reference: accelerator.py:782-1120)
    # ------------------------------------------------------------------

    def on_main_process(self, function):
        return self.state.on_main_process(function)

    def on_local_main_process(self, function):
        return self.state.on_local_main_process(function)

    def on_last_process(self, function):
        return self.state.on_last_process(function)

    def on_process(self, function=None, process_index=None):
        if function is None:
            return functools.partial(self.on_process, process_index=process_index)
        return self.state.on_process(function, process_index)

    def on_local_process(self, function=None, local_process_index=None):
        if function is None:
            return functools.partial(self.on_local_process, local_process_index=local_process_index)
        return self.state.on_local_process(function, local_process_index)

    @contextlib.contextmanager
    def main_process_first(self):
        with self.state.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.state.local_main_process_first():
            yield

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.state.split_between_processes(inputs, apply_padding=apply_padding)

    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    def print(self, *args, **kwargs):
        self.state.print(*args, **kwargs)

    # ------------------------------------------------------------------
    # prepare() — the core (reference: accelerator.py:1414-1570)
    # ------------------------------------------------------------------

    def prepare(self, *args, device_placement=None):
        """Prepare model/optimizer/dataloader/scheduler objects, returning
        them in the same order (reference: accelerator.py:1414).

        Optimizer pairing contract: each optax optimizer binds to the nearest
        model *before* it in the argument list that doesn't already have one —
        ``prepare(model, tx)``, ``prepare(gen, gen_tx, disc, disc_tx)``,
        ``prepare(student, tx, frozen_teacher)`` and
        ``prepare(frozen_teacher, student, tx)`` all do what they look like.
        An optimizer before any model, or two optimizers after the same model,
        raises. (The torch reference pairs via param references; a functional
        tx has none, so argument adjacency is the contract.)
        """
        result = []
        models = [a for a in args if isinstance(a, Model)]
        for model in models:
            if self.verify_device_map(model):
                # Same guard as the reference (accelerator.py:3744-3760): a
                # model dispatched across HBM/host/disk cannot also be
                # prepared for distributed training — its params aren't a
                # mesh-shardable tree.
                raise ValueError(
                    "You can't train a model that has been dispatched with a "
                    "multi-placement device_map (offloaded to cpu/disk). Load the "
                    "model on-device (or shard it with a ParallelismConfig mesh) "
                    "before calling prepare()."
                )

        # Models pair with optimizers by ADJACENCY in args order: each
        # optimizer binds to the nearest *preceding* model that does not have
        # one yet (the torch reference gets pairing implicitly from param
        # references; a functional optax tx has none, so argument order is
        # the contract). prepare(frozen_teacher, student, tx) therefore binds
        # tx to `student`; two optimizers after the same model is ambiguous
        # and raises. A model without a following optimizer is prepared
        # inference-only (e.g. a frozen teacher).
        pairings: list = [None] * len(models)  # models index -> tx
        tx_models: list = []  # per tx, in args order: the Model it binds to
        cur = -1  # index into `models` of the most recent model seen
        for obj in args:
            if isinstance(obj, Model):
                cur += 1
            elif _is_optax_tx(obj):
                if not models:
                    tx_models.append(None)  # lone optimizer: wrap unbound
                    continue
                if cur < 0:
                    raise ValueError(
                        "prepare() received an optimizer before any model; pass "
                        "each optimizer after the model it should train, e.g. "
                        "prepare(model, tx, dataloader)."
                    )
                if pairings[cur] is not None:
                    raise ValueError(
                        "prepare() optimizer pairing is ambiguous: two optimizers "
                        "follow the same model. Pass each optimizer immediately "
                        "after its own model, e.g. prepare(gen, gen_tx, disc, "
                        "disc_tx)."
                    )
                pairings[cur] = obj
                tx_models.append(models[cur])
        if models and self._auto_plan_pending:
            # Resolve the auto-parallelism plan from the FIRST prepared model
            # before any mesh-dependent planning happens (planner.py).
            self._resolve_auto_plan(models[0])
        for i, model in enumerate(models):
            self._prepare_state(model, pairings[i])
        tx_seen = 0

        for obj in args:
            if isinstance(obj, Model):
                result.append(self.prepare_model(obj))
            elif _is_optax_tx(obj):
                # Pairing already bound this tx to its model's slot above;
                # prepare_optimizer must only wrap, not re-bind it to some
                # other (optimizer-less) slot — e.g. a frozen teacher.
                bound = tx_models[tx_seen]
                result.append(
                    self.prepare_optimizer(
                        obj,
                        _already_bound=bound is not None,
                        _bound_slot=bound._state_slot if bound is not None else None,
                    )
                )
                tx_seen += 1
            elif isinstance(obj, AcceleratedOptimizer):
                result.append(obj)
            elif _is_dataloader_like(obj):
                result.append(self.prepare_data_loader(obj))
            elif _is_schedule(obj):
                result.append(self.prepare_scheduler(obj))
            else:
                result.append(obj)
        self._maybe_elastic_resume()
        if self.fault_tolerance is not None:
            # Rank-coherent by construction: every rank runs prepare(), and
            # the launcher signals the whole local gang (multi-host coherence
            # goes through check_preemption's collective).
            self.fault_tolerance.install_signal_handlers()
            self.fault_tolerance.start_watchdog()
        return result[0] if len(result) == 1 else tuple(result)

    def _maybe_elastic_resume(self) -> None:
        """Elastic auto-resume: when the launcher restarted the gang
        (ACCELERATE_RESTART_ATTEMPT > 0, commands/launch.py gang loop) and
        the project saves automatic checkpoints, restore the latest one
        right after prepare() — a restarted run must continue, not silently
        train from scratch. Opt-in via
        ProjectConfiguration(automatic_resume=True); reference analog:
        torch elastic restarts (launch.py:998-1030) + the script-side
        resume_from_checkpoint idiom."""
        pc = self.project_configuration
        if not (pc.automatic_resume and pc.automatic_checkpoint_naming):
            return
        if getattr(self, "_elastic_resumed", False):
            # Staged prepares: dataloaders/schedulers/custom objects
            # registered AFTER the resume still need their checkpointed
            # host-side state. Safe to re-apply only while no training has
            # happened since the resume (the rewind hazard needs steps).
            resume_dir = getattr(self, "_elastic_resume_dir", None)
            if (
                resume_dir is not None
                and self._host_state_counts() != getattr(self, "_elastic_resume_counts", None)
                and int(np.asarray(self._train_state.step))
                == getattr(self, "_elastic_resume_step", -1)
            ):
                from .checkpointing import _load_host_side_state

                _load_host_side_state(self, resume_dir)
                self._elastic_resume_counts = self._host_state_counts()
            return
        attempt = int(os.environ.get("ACCELERATE_RESTART_ATTEMPT", "0") or 0)
        if attempt <= 0:
            return
        # Wait for a prepare() that produced a *trainable* state: a staged
        # script may prepare dataloaders (no train state) or a frozen model
        # (no tx) first — resuming then would crash or skip the optimizer
        # moments, and the consumed flag would block the real resume later.
        state = self._train_state
        if state is None or state.tx is None:
            return
        # From here the decision is final for this process, including the
        # fresh-start path: a later prepare() call mid-training must never
        # rewind to a checkpoint the run itself has since written.
        self._elastic_resumed = True
        base = os.path.join(self.project_dir or ".", "checkpoints")
        from .checkpointing import _list_checkpoint_dirs

        # _list_checkpoint_dirs, not a bare startswith() scan: a restart whose
        # ONLY artifact is an interrupted checkpoint_N.tmp staging dir must
        # start fresh, not crash load_state on an empty resolver result.
        if not os.path.isdir(base) or not _list_checkpoint_dirs(base):
            logger.warning(
                "automatic_resume: restart attempt %d but no checkpoints under "
                "%s — starting fresh.", attempt, base,
            )
            return
        loaded = self.load_state()
        self._elastic_resume_dir = loaded
        self._elastic_resume_counts = self._host_state_counts()
        self._elastic_resume_step = int(np.asarray(self._train_state.step))
        logger.info(
            "automatic_resume: restart attempt %d resumed from %s (step %d)",
            attempt, loaded, self._elastic_resume_step,
            main_process_only=True,
        )

    def _host_state_counts(self) -> tuple:
        """Registration counts of everything _load_host_side_state restores
        by enumeration — the staleness key for staged elastic resume."""
        return (
            len(self._dataloaders),
            len(self._schedulers),
            len(self._custom_objects),
        )

    def _resolve_auto_plan(self, model: Model) -> None:
        """Auto-parallelism (planner.py): search — or load the cached —
        :class:`~accelerate_tpu.planner.ParallelPlan` for ``model`` on this
        process's devices, install its layout as the ParallelismConfig, and
        apply its remat/microbatch decisions. Runs at most once, from the
        first prepare() that sees a model."""
        self._auto_plan_pending = False
        handler = self.auto_plan_handler
        if self.state.parallelism_config is not None:
            logger.warning(
                "auto-plan: an explicit ParallelismConfig is already set — "
                "the planner defers to it (drop parallelism_config= to let "
                "the search choose)."
            )
            return
        if self.state._mesh is not None:
            raise RuntimeError(
                "auto-plan: the device mesh was already built (something "
                "touched accelerator.mesh before prepare()). Construct the "
                "Accelerator with parallelism_config='auto' and prepare the "
                "model before any mesh access."
            )
        module = getattr(model, "module", None)
        cfg = getattr(module, "config", None)
        if module is None or cfg is None:
            raise ValueError(
                "auto-plan needs an in-framework module carrying a config "
                "(divisibility constraints + activation model); wrap your "
                "model with Model.from_flax(module, ...) where module.config "
                "exists, or pass an explicit ParallelismConfig."
            )
        from .planner import BandwidthTable, Planner, default_tp_rules, layout_str

        # Elastic resize: a relaunch that came back on a different device
        # count re-searches under the new topology, pinning what the previous
        # run's (calibrated) plan says is winning — or, under
        # resize_policy="keep", pinning the whole scaled layout.
        pinned = handler.pinned
        if not pinned:
            pinned = self._elastic_resize_pins() or pinned
        label = f"{type(cfg).__name__}:{getattr(cfg, 'num_hidden_layers', '?')}L"
        planner = Planner(
            module,
            cfg,
            n_devices=len(self.state.devices),
            hbm_gib=handler.hbm_gib,
            seq=handler.seq,
            per_chip_batch=handler.per_chip_batch,
            optimizer=handler.optimizer,
            tp_rules=model.tp_rules or default_tp_rules(module, cfg),
            axes=tuple(handler.axes),
            pinned=pinned,
            bandwidths=BandwidthTable.from_dict(handler.bandwidths),
            label=label,
        )
        plans_dir = handler.plans_dir or os.path.join(
            self.project_dir or ".", "plans"
        )
        plan, path, from_cache = planner.resolve(
            plans_dir, use_cache=handler.use_cache
        )
        self.active_plan = plan
        self.active_plan_meta = {"path": path, "from_cache": from_cache}
        pc = plan.to_parallelism_config()
        self.state.parallelism_config = pc
        if pc.tp_size > 1 and not model.tp_rules and planner.tp_rules:
            # Train with the SAME rule table the plan was priced with —
            # otherwise a tp>1 layout would silently replicate every leaf.
            model.tp_rules = list(planner.tp_rules)
        logger.info(
            "auto-plan: %s layout %s (predicted %.4gs/step, %.3g GiB/chip%s)"
            " — artifact %s",
            "loaded cached" if from_cache else "searched",
            layout_str(plan.layout), plan.predicted_step_s,
            plan.predicted_hbm_gib,
            ", OVER BUDGET" if plan.over_budget else "",
            path,
            main_process_only=True,
        )
        if plan.over_budget:
            logger.warning(
                "auto-plan: no layout fit %.1f GiB/chip — training with the "
                "best-effort plan %s (predicted %.3g GiB). Expect OOM; see "
                "the plan's rejection log (%s) and docs/usage_guides/"
                "auto_parallelism.md.",
                plan.hbm_gib_budget, layout_str(plan.layout),
                plan.predicted_hbm_gib, path,
            )
        # Apply the remat decision the plan priced (same rebuild contract as
        # fsdp_plugin.activation_checkpointing).
        if handler.apply_remat and plan.remat and getattr(cfg, "remat", None) is False:
            import dataclasses as _dc

            new_module = type(module)(
                _dc.replace(cfg, remat=True, remat_policy=plan.remat_policy)
            )
            model.module = new_module
            model.apply_fn = new_module.apply
            logger.info(
                "auto-plan: enabled remat (policy=%s) on %s per the plan.",
                plan.remat_policy, type(module).__name__,
                main_process_only=True,
            )
        if (
            handler.apply_microbatches
            and plan.microbatches > 1
            and self.gradient_state.num_steps == 1
        ):
            self.gradient_accumulation_steps = plan.microbatches
            logger.info(
                "auto-plan: gradient_accumulation_steps=%d per the plan's "
                "microbatch ladder.", plan.microbatches,
                main_process_only=True,
            )
        if self.telemetry is not None:
            self.telemetry.note_plan(
                plan.to_json_dict(), path,
                calibrate_after=handler.calibrate_after,
            )
        if self.compile_manager is not None:
            self.compile_manager.note_plan(plan)

    def _checkpoint_plan_layout(self) -> Optional[dict]:
        """Layout recorded in the newest checkpoint's plan manifest, or None
        (no checkpoints / checkpoint predates plan manifests)."""
        base = os.path.join(self.project_dir or ".", "checkpoints")
        if not os.path.isdir(base):
            return None
        from .checkpointing import _list_checkpoint_dirs
        from .resharding import read_plan_manifest

        for name in reversed(_list_checkpoint_dirs(base)):
            manifest = read_plan_manifest(os.path.join(base, name))
            if manifest is not None:
                return manifest.get("layout") or None
        return None

    def _elastic_resize_pins(self) -> Optional[dict]:
        """Planner pins for the preemption-driven resize path: only active on
        an elastic relaunch (``ACCELERATE_RESTART_ATTEMPT`` > 0) with an
        ElasticKwargs handler and a checkpointed source layout to learn
        from. ``resize_policy="fail"`` pins nothing — the restore itself will
        raise on the mismatch."""
        elastic = self.elastic
        attempt = int(os.environ.get("ACCELERATE_RESTART_ATTEMPT", "0") or 0)
        if elastic is None or not elastic.enabled or attempt <= 0:
            return None
        if elastic.resize_policy == "fail":
            return None
        src_layout = self._checkpoint_plan_layout()
        if not src_layout:
            return None
        from .planner import layout_str, resize_pins, scaled_layout

        n_dev = len(self.state.devices)
        pins: Optional[dict] = None
        if elastic.resize_policy == "keep":
            kept = scaled_layout(src_layout, n_dev)
            if kept is not None:
                # Pin every plannable axis: the "search" then has exactly one
                # candidate — the old layout with dp rescaled — but still
                # produces a normal plan artifact + telemetry.
                pins = {
                    ax: int(kept.get(ax, 1))
                    for ax in ("dp_replicate", "dp_shard", "tp", "cp", "pp")
                }
            # Non-divisible "keep" falls through to winning-axes pinning.
        if pins is None and getattr(elastic.handler, "pin_winning_axes", True):
            pins = resize_pins(src_layout, n_dev) or None
        if pins:
            logger.info(
                "elastic resize: restart attempt %d on %d device(s) — "
                "planner re-search pinned to %s (checkpoint layout was %s).",
                attempt, n_dev, pins, layout_str(src_layout),
                main_process_only=True,
            )
        return pins

    def _apply_activation_checkpointing(self, model: Model):
        """Honor ``fsdp_plugin.activation_checkpointing`` (reference FSDP
        ``activation_checkpointing=True`` wraps blocks in
        checkpoint_wrapper): flagship modules expose ``config.remat`` — flip
        it and rebuild the module. Warn loudly when the module has no remat
        knob; a silently-ignored flag is worse than none."""
        plugin = self.fsdp_plugin
        if plugin is None or not plugin.activation_checkpointing:
            return
        module = model.module
        cfg = getattr(module, "config", None)
        if cfg is not None and getattr(cfg, "remat", None) is False:
            import dataclasses as _dc

            new_module = type(module)(_dc.replace(cfg, remat=True))
            model.module = new_module
            model.apply_fn = new_module.apply
            logger.warning(
                "activation_checkpointing: rebuilt %s with config.remat=True. "
                "Write your loss_fn against model.module / model(batch) — a "
                "loss_fn closing over the module object created before "
                "prepare() still traces the un-rematted version.",
                type(module).__name__,
            )
        elif cfg is None or not hasattr(cfg, "remat"):
            logger.warning(
                "fsdp_plugin.activation_checkpointing=True but %s has no "
                "config.remat knob — apply jax.checkpoint/nn.remat inside your "
                "module to get activation checkpointing.",
                type(module).__name__,
            )

    def _prepare_state(self, model: Model, tx):
        """Plan shardings for params + optimizer state and build the canonical
        TrainState on the mesh. This is where FSDP/ZeRO/HSDP/TP all happen
        (SURVEY.md §7: the backend zoo collapses into NamedSharding choices)."""
        self._apply_activation_checkpointing(model)
        mesh = self.mesh
        cfg = self.state.parallelism_config or ParallelismConfig()
        if (model._params if model._params is not None else model.params) is None:
            raise RuntimeError(
                "Model has no reachable params — it was prepared by a previous "
                "Accelerator whose state is gone. Rebuild it (Model.from_flax "
                "or load a checkpoint) before preparing it again."
            )
        param_shardings = plan_parameter_sharding(
            model._params if model._params is not None else model.params,
            mesh,
            fsdp_plugin=self.fsdp_plugin,
            parallelism_config=cfg,
            tp_rules=model.tp_rules,
        )
        params = jax.tree.map(
            lambda p, s: jax.device_put(jnp.asarray(p), s),
            model._params if model._params is not None else model.params,
            param_shardings,
        )
        loss_scale = None
        if self.state.mixed_precision == "fp16":
            kw = self.scaler_handler.to_kwargs() if self.scaler_handler else {}
            if kw.pop("enabled", True):
                loss_scale = DynamicLossScale.create(
                    init_scale=kw.pop("init_scale", 2.0**16),
                    **{k: v for k, v in kw.items() if k in ("growth_factor", "backoff_factor", "growth_interval")},
                )
        if tx is not None:
            opt_shardings, grad_shardings, opt_offload = self._build_opt_shardings(
                model, params, param_shardings, tx, cfg
            )
            opt_init = jax.jit(tx.init, out_shardings=opt_shardings)
            opt_state = opt_init(params)
        else:
            opt_state, opt_shardings = (), ()
            grad_shardings, opt_offload = None, None
        rep = replicated(mesh)
        extra = model.extra_state
        extra_shardings = jax.tree.map(lambda _: replicated(mesh), extra) if extra else None
        # Every leaf is COMMITTED from the start (step/loss_scale/extra too,
        # not just params/opt_state): an uncommitted scalar in the initial
        # state gives the first step call different input avals than every
        # later call (whose state is the step's committed output), costing
        # one extra executable per step fn — the "layout (expected once)"
        # recompile the telemetry watchdog used to tolerate.
        state = TrainState(
            step=jax.device_put(jnp.asarray(0, jnp.int32), rep),
            params=params,
            opt_state=opt_state,
            extra_state=jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), rep), extra)
            if extra
            else extra,
            accum_grads=None,
            loss_scale=jax.tree.map(lambda x: jax.device_put(x, rep), loss_scale)
            if loss_scale is not None
            else None,
            apply_fn=model.apply_fn,
            tx=tx,
        )
        state_shardings = TrainState(
            step=rep,
            params=param_shardings,
            opt_state=opt_shardings,
            extra_state=extra_shardings,
            accum_grads=None,
            loss_scale=jax.tree.map(lambda _: rep, state.loss_scale) if loss_scale is not None else None,
            apply_fn=model.apply_fn,
            tx=tx,
        )
        # Commit into this model's slot; the flat attrs mirror slot 0 (the
        # legacy single-model surface).
        meta = {
            "state_shardings": state_shardings,
            "param_shardings": param_shardings,
            "grad_shardings": grad_shardings,
            "opt_offload": opt_offload,
        }
        slot = getattr(model, "_state_slot", None)
        if getattr(model, "_accelerator", None) is not None and model._accelerator is not self:
            slot = None  # model was bound to a previous Accelerator; its slot is stale
        if slot is None or slot >= len(self._train_states):
            slot = len(self._train_states)
            self._train_states.append(state)
            self._slot_meta.append(meta)
        else:
            self._train_states[slot] = state
            self._slot_meta[slot] = meta
        model._state_slot = slot
        model._accelerator = self  # bind now so prepare_model won't re-prepare
        if slot == 0:
            self._state_shardings = state_shardings
            self._param_shardings = param_shardings
            self._grad_shardings = grad_shardings
            self._opt_offload = opt_offload

    def _plan_opt_shardings(self, model, param_shardings, mesh, cfg):
        """ZeRO-1/2 (SHARD_GRAD_OP) + cpu_offload planning.

        SHARD_GRAD_OP keeps params replicated but shards gradients and
        optimizer state over ``dp_shard`` (reference FSDP sharding_strategy /
        DeepSpeed stages 1-2, utils/dataclasses.py:1584-2190,
        utils/deepspeed.py:253-293). HBM per chip for N params (bf16 compute,
        fp32 Adam) on a W-way dp_shard axis:

          FULL_SHARD:    (2N params + 2N grads + 12N opt) / W
          SHARD_GRAD_OP:  2N params + (2N grads + 12N opt) / W
          NO_SHARD:       2N + 2N + 12N

        ``cpu_offload=True`` additionally pins the optimizer state to
        ``pinned_host`` memory — XLA's host-offload path streams it per update
        instead of the reference's CPUOffload module wrapper.

        Pure planner: returns (opt sharding plan tree, memory_kind or None,
        grad shardings or None — the ZeRO-2 reduce-scatter constraint for
        prepare_train_step). Callers commit the plans into the slot meta."""
        plugin = self.fsdp_plugin
        grad_shardings = None
        opt_plan = param_shardings
        if plugin is not None and plugin.shards_grads_and_opt and not plugin.shards_params:
            params_tree = model._params if model._params is not None else model.params
            opt_plan = plan_parameter_sharding(
                params_tree,
                mesh,
                fsdp_plugin=plugin,
                parallelism_config=cfg,
                tp_rules=model.tp_rules,
                shards_params_override=True,
            )
            grad_shardings = opt_plan
        mem_kind = None
        if plugin is not None and plugin.cpu_offload:
            # Host offload is a TPU-runtime feature; the CPU backend accepts
            # the memory-kind annotation but its SPMD partitioner rejects it
            # at compile time, so gate on platform rather than probing.
            if self.device.platform in ("tpu", "axon"):
                mem_kind = "pinned_host"
            else:
                logger.warning(
                    "fsdp_plugin.cpu_offload requested but backend %s has no "
                    "host memory space — optimizer state stays in device memory.",
                    self.device.platform,
                )
        return opt_plan, mem_kind, grad_shardings

    def _build_opt_shardings(self, model, params, param_shardings, tx, cfg):
        """Shared by _prepare_state and prepare_optimizer: plan optimizer-state
        shardings (ZeRO strategy + cpu_offload). Pure: returns
        (storage opt shardings — host-pinned under cpu_offload,
        grad shardings or None, opt_offload pair or None); callers commit
        them into the slot meta (flat attrs mirror slot 0 only)."""
        opt_plan, mem_kind, grad_shardings = self._plan_opt_shardings(
            model, param_shardings, self.mesh, cfg
        )
        opt_shapes = jax.eval_shape(tx.init, params)
        opt_shardings = infer_opt_state_sharding(
            opt_shapes, params, opt_plan, self.mesh, memory_kind=mem_kind
        )
        if mem_kind is not None:
            # Host-offloaded optimizer state: the fused step streams it to
            # device around tx.update (see prepare_train_step).
            device_shardings = infer_opt_state_sharding(opt_shapes, params, opt_plan, self.mesh)
            opt_offload = (device_shardings, opt_shardings)
        else:
            opt_offload = None
        return opt_shardings, grad_shardings, opt_offload

    def prepare_model(self, model: Model, device_placement=None, evaluation_mode: bool = False) -> Model:
        if (
            getattr(model, "_state_slot", None) is None
            or getattr(model, "_accelerator", None) is not self
        ):
            # Also re-prepare a model carrying a slot from a PREVIOUS
            # Accelerator — its stale slot index must not alias this
            # accelerator's states (and _params may need re-materializing).
            self._prepare_state(model, None)
        model._accelerator = self
        model._params = None  # canonical copy now lives in the TrainState
        model._accelerate_prepared = True
        if model not in self._models:
            self._models.append(model)
        return model

    def prepare_optimizer(
        self,
        optimizer,
        device_placement=None,
        _already_bound: bool = False,
        _bound_slot: Optional[int] = None,
    ) -> AcceleratedOptimizer:
        if isinstance(optimizer, AcceleratedOptimizer):
            return optimizer
        wrapped = AcceleratedOptimizer(
            optimizer, device_placement=device_placement or self.device_placement, accelerator=self
        )
        wrapped._state_slot = _bound_slot if _already_bound else None
        # Bind to the first prepared model still missing an optimizer (slot
        # order == order of appearance in prepare()); skipped when prepare()'s
        # model/optimizer pairing already bound this tx.
        slot = (
            None
            if _already_bound
            else next((i for i, st in enumerate(self._train_states) if st.tx is None), None)
        )
        if slot is not None:
            state = self._train_states[slot]
            model = next(
                (m for m in self._models if getattr(m, "_state_slot", None) == slot),
                self._models[-1] if self._models else None,
            )
            if slot >= len(self._slot_meta):
                # State installed directly (not via _prepare_state): keep the
                # flat-attr plans as its meta.
                self._slot_meta.extend(
                    {"state_shardings": self._state_shardings,
                     "param_shardings": self._param_shardings,
                     "grad_shardings": self._grad_shardings,
                     "opt_offload": self._opt_offload}
                    for _ in range(slot + 1 - len(self._slot_meta))
                )
            meta = self._slot_meta[slot]
            param_shardings = meta["param_shardings"]
            cfg = self.state.parallelism_config or ParallelismConfig()
            if model is not None:
                opt_shardings, grad_shardings, opt_offload = self._build_opt_shardings(
                    model, state.params, param_shardings, optimizer, cfg
                )
                meta["grad_shardings"] = grad_shardings
                meta["opt_offload"] = opt_offload
                if slot == 0:
                    self._grad_shardings = grad_shardings
                    self._opt_offload = opt_offload
            else:
                opt_shapes = jax.eval_shape(optimizer.init, state.params)
                opt_shardings = infer_opt_state_sharding(
                    opt_shapes, state.params, param_shardings, self.mesh
                )
            opt_state = jax.jit(optimizer.init, out_shardings=opt_shardings)(state.params)
            self._train_states[slot] = state.replace(opt_state=opt_state, tx=optimizer)
            wrapped._state_slot = slot
            meta["state_shardings"] = meta["state_shardings"].replace(
                opt_state=opt_shardings, tx=optimizer
            )
            if slot == 0:
                self._state_shardings = meta["state_shardings"]
        self._optimizers.append(wrapped)
        return wrapped

    def prepare_data_loader(self, data_loader, device_placement=None, slice_fn_for_dispatch=None):
        if isinstance(data_loader, BaseDataLoader):
            if data_loader not in self._dataloaders:
                self._dataloaders.append(data_loader)
            data_loader._telemetry = self.telemetry
            data_loader._compile_manager = self.compile_manager
            data_loader._fault_tolerance = self.fault_tolerance
            return data_loader
        cfg = self.dataloader_config
        prepared = prepare_data_loader(
            data_loader,
            num_processes=self.num_processes,
            process_index=self.process_index,
            split_batches=cfg.split_batches,
            put_on_device=device_placement if device_placement is not None else self.device_placement,
            rng_types=self.rng_types,
            dispatch_batches=cfg.dispatch_batches,
            even_batches=cfg.even_batches,
            use_seedable_sampler=cfg.use_seedable_sampler,
            data_seed=cfg.data_seed,
            non_blocking=cfg.non_blocking,
            prefetch_size=cfg.prefetch_size,
            dispatch_group_size=cfg.dispatch_group_size,
        )
        prepared._telemetry = self.telemetry  # host-wait accounting hook
        prepared._compile_manager = self.compile_manager  # bucket padding hook
        prepared._fault_tolerance = self.fault_tolerance  # chaos corrupt_batch hook
        self._dataloaders.append(prepared)
        return prepared

    def prepare_scheduler(self, scheduler) -> AcceleratedScheduler:
        if isinstance(scheduler, AcceleratedScheduler):
            return scheduler
        wrapped = AcceleratedScheduler(
            scheduler,
            optimizers=self._optimizers or None,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.split_batches,
        )
        self._schedulers.append(wrapped)
        self._scheduler = wrapped
        return wrapped

    # ------------------------------------------------------------------
    # Gradient accumulation (reference: accelerator.py:1131-1381)
    # ------------------------------------------------------------------

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Context manager flipping ``sync_gradients`` on accumulation
        boundaries (reference: accelerator.py:1255-1297). Under GSPMD there is
        no allreduce to skip — skipping the *optimizer update* is the whole
        story — so `no_sync` semantics are free."""
        self._do_sync()
        with contextlib.nullcontext():
            yield

    def _do_sync(self):
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients(
                (self.step % self.gradient_state.num_steps) == 0
            )

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """(reference: accelerator.py:1131-1178) — a no-op under GSPMD; kept
        for API parity."""
        old = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(old)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """Under even_batches sharding every rank always has a batch, so this
        is advisory; the ``even_batches`` override applies only inside the
        context, like the reference's (reference: accelerator.py:1299-1381)."""
        overridden = []
        if even_batches is not None:
            for dl in self._dataloaders:
                if hasattr(dl, "batch_sampler") and hasattr(dl.batch_sampler, "even_batches"):
                    overridden.append((dl.batch_sampler, dl.batch_sampler.even_batches))
                    dl.batch_sampler.even_batches = even_batches
        try:
            yield
        finally:
            for sampler, old in overridden:
                sampler.even_batches = old

    # ------------------------------------------------------------------
    # Imperative training surface (reference: accelerator.py:2818-2999)
    # ------------------------------------------------------------------

    def backward(self, loss_fn: Callable, *args, has_aux: bool = False, **kwargs):
        """Compute gradients of ``loss_fn(params, *args, **kwargs)`` w.r.t.
        the prepared params and accumulate them.

        This is the one necessary deviation from the reference's
        ``backward(loss)``: JAX differentiates *functions*, not scalars. The
        loss is divided by the accumulation step count exactly like the
        reference (accelerator.py:2840), and gradients arrive DP-averaged
        because batch + loss-mean are globally sharded.

        Returns the (unscaled) loss value, plus aux if ``has_aux``.
        """
        if self._train_state is None:
            raise RuntimeError("Call accelerator.prepare(...) before backward().")
        key = (loss_fn, has_aux)
        if key not in self._grad_fn_cache:
            policy = self._mp_policy
            num_steps_ref = self.gradient_state

            def _scaled_loss(params, scale, n_accum, *f_args, **f_kwargs):
                compute_params = policy.cast_for_compute(params)
                out = loss_fn(compute_params, *f_args, **f_kwargs)
                loss, aux = (out if has_aux else (out, None))
                scaled = loss / n_accum * scale
                return scaled.astype(jnp.float32), (loss, aux)

            grad_fn = jax.value_and_grad(_scaled_loss, has_aux=True)

            def _run(params, scale, n_accum, *f_args, **f_kwargs):
                (_, (loss, aux)), grads = grad_fn(params, scale, n_accum, *f_args, **f_kwargs)
                return loss, aux, grads

            self._grad_fn_cache[key] = jax.jit(_run)
        scale = (
            self._train_state.loss_scale.scale
            if self._train_state.loss_scale is not None
            else jnp.asarray(1.0, jnp.float32)
        )
        n_accum = jnp.asarray(float(self.gradient_state.num_steps), jnp.float32)
        tel = self.telemetry
        t0 = time.perf_counter() if tel is not None else 0.0
        loss, aux, grads = self._grad_fn_cache[key](
            self._train_state.params, scale, n_accum, *args, **kwargs
        )
        if tel is not None:
            if tel.handler.sync_timing:
                jax.block_until_ready(loss)
            tel.on_backward(self._grad_fn_cache[key], (args, kwargs), time.perf_counter() - t0)
        if self._optimizers:
            self._optimizers[0].accumulate_grads(grads)
        else:
            if self._train_state.accum_grads is None:
                self._train_state = self._train_state.replace(accum_grads=grads)
            else:
                self._train_state = self._train_state.replace(
                    accum_grads=jax.tree.map(jnp.add, self._train_state.accum_grads, grads)
                )
        return (loss, aux) if has_aux else loss

    def _apply_gradients(self, grads) -> bool:
        """Jitted optimizer update with clipping + fp16 overflow skip.
        Returns True when the step was applied."""
        if self._apply_jit is None:
            tx = self._train_state.tx

            def _apply(state: TrainState, grads, max_norm, clip_enabled):
                if state.loss_scale is not None:
                    grads = state.loss_scale.unscale(grads)
                finite = grads_all_finite(grads) if state.loss_scale is not None else jnp.asarray(True)
                if clip_enabled:
                    gnorm = optax.global_norm(grads)
                    factor = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
                    grads = jax.tree.map(lambda g: g * factor, grads)
                else:
                    gnorm = optax.global_norm(grads)
                updates, new_opt = tx.update(grads, state.opt_state, state.params)
                new_params = optax.apply_updates(state.params, updates)
                # fp16 overflow → keep old params/opt, still advance scale state.
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old), new_params, state.params
                )
                new_opt = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old) if hasattr(new, "shape") else new,
                    new_opt,
                    state.opt_state,
                )
                new_scale = (
                    state.loss_scale.update(finite) if state.loss_scale is not None else None
                )
                new_state = state.replace(
                    step=state.step + jnp.where(finite, 1, 0),
                    params=new_params,
                    opt_state=new_opt,
                    loss_scale=new_scale,
                )
                return new_state, finite, gnorm

            self._apply_jit = jax.jit(
                _apply, static_argnames=("clip_enabled",), donate_argnums=(0, 1)
            )
        max_norm = jnp.asarray(self._max_grad_norm or 0.0, jnp.float32)
        tel = self.telemetry
        t0 = time.perf_counter() if tel is not None else 0.0
        new_state, finite, gnorm = self._apply_jit(
            self._train_state, grads, max_norm, self._max_grad_norm is not None
        )
        applied = bool(finite)  # host fetch — the barrier telemetry times against
        self._train_state = new_state
        self._last_grad_norm = gnorm
        if tel is not None:
            tel.on_apply_gradients(time.perf_counter() - t0)
        return applied

    def clip_grad_norm_(self, parameters=None, max_norm: float = 1.0, norm_type: float = 2.0):
        """Arm gradient clipping for the next optimizer step and return the
        current accumulated-grad global norm (reference: accelerator.py:2946).
        ``parameters`` is accepted for signature parity and ignored — clipping
        always applies to the prepared state's grads."""
        if norm_type != 2.0:
            raise NotImplementedError("Only L2 grad-norm clipping is supported (MXU-friendly).")
        self._max_grad_norm = float(max_norm)
        grads = self._optimizers[0].grads if self._optimizers else self._train_state.accum_grads
        if grads is None:
            return None
        if self._gradnorm_jit is None:
            self._gradnorm_jit = jax.jit(optax.global_norm)
        return self._gradnorm_jit(grads)

    def clip_grad_value_(self, parameters=None, clip_value: float = 1.0):
        raise NotImplementedError(
            "clip_grad_value_ is not supported; use clip_grad_norm_ (value-clipping "
            "breaks DP-mean linearity and is rarely used on TPU)."
        )

    # ------------------------------------------------------------------
    # Fused train step — the fast path
    # ------------------------------------------------------------------

    def prepare_train_step(
        self,
        loss_fn: Callable,
        *,
        has_aux: bool = False,
        mutable_state: bool = False,
        max_grad_norm: Optional[float] = None,
        donate: Optional[bool] = None,
        model: Optional[Model] = None,
    ) -> Callable:
        """Build ONE jitted step: ``step(state, batch) -> (state, metrics)``.

        - grad accumulation folds in as a ``lax.scan`` over microbatches: when
          ``gradient_accumulation_steps > 1`` one call consumes the FULL
          optimizer batch with a leading accumulation axis. Prepared
          dataloaders add that axis automatically (host-side reshape of each
          process's local shard keeps the dp sharding layout exact).
        - precision policy: params cast to compute dtype at use; fp32 masters
          updated; fp16 loss scaling handled.
        - ``donate``: state buffers are donated so params/opt-state update in
          place in HBM (default from JitConfig).
        - ``mutable_state``: for models carrying non-param collections that
          the forward updates (flax ``batch_stats`` — BatchNorm). The loss fn
          then takes ``(params, extra_state, batch)`` and returns
          ``(loss, new_extra_state)``; the step threads the updated
          collections through ``state.extra_state``. Because the batch axis
          is dp-sharded under GSPMD, BatchNorm's batch reductions compile to
          cross-device means — sync-BN semantics with no extra machinery
          (the reference needs SyncBatchNorm conversion for this).
        """
        if not self._train_states:
            raise RuntimeError("Call accelerator.prepare(...) first.")
        if mutable_state and has_aux:
            raise ValueError("mutable_state and has_aux are mutually exclusive")
        # Multi-model: `model=` selects whose TrainState this step advances
        # (each prepared model owns a slot); default is the primary.
        slot = 0
        if model is not None:
            slot = getattr(model, "_state_slot", None)
            if slot is None or model._accelerator is not self:
                raise ValueError("model was not prepared by this Accelerator")
        if donate is None:
            donate = self.jit_config.donate_state
        policy = self._mp_policy
        tx = self._train_states[slot].tx
        num_accum = self.gradient_state.num_steps
        if max_grad_norm is None:
            # Migrated ds_config gradient_clipping applies like the DS engine
            # applied it (DeepSpeedPlugin.from_ds_json).
            max_grad_norm = self._ds_gradient_clipping
        clip_enabled = max_grad_norm is not None
        max_norm = float(max_grad_norm or 0.0)
        meta = (
            self._slot_meta[slot]
            if slot < len(self._slot_meta)
            else {"grad_shardings": self._grad_shardings, "opt_offload": self._opt_offload}
        )
        grad_shardings = meta["grad_shardings"]  # ZeRO-2: reduce-scatter grads

        def _loss_and_grads(params, extra, loss_scale, microbatch):
            def _fn(p):
                if mutable_state:
                    loss, new_extra = loss_fn(policy.cast_for_compute(p), extra, microbatch)
                    aux = None
                else:
                    out = loss_fn(policy.cast_for_compute(p), microbatch)
                    loss, aux = (out if has_aux else (out, None))
                    new_extra = extra
                scale = loss_scale.scale if loss_scale is not None else 1.0
                return (loss * scale).astype(jnp.float32), (loss, aux, new_extra)

            (_, (loss, aux, new_extra)), grads = jax.value_and_grad(_fn, has_aux=True)(params)
            if grad_shardings is not None:
                # SHARD_GRAD_OP: constrain grads to the opt-state sharding so
                # GSPMD lowers the DP grad sync as reduce-scatter (each chip
                # keeps only its 1/W slice) instead of all-reduce.
                grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            return loss, aux, new_extra, grads

        opt_offload = meta["opt_offload"]  # (device shardings, host shardings) | None

        def _update(state: TrainState, grads):
            if state.loss_scale is not None:
                grads = state.loss_scale.unscale(grads)
                finite = grads_all_finite(grads)
            else:
                finite = jnp.asarray(True)
            gnorm = optax.global_norm(grads)
            if clip_enabled:
                factor = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)
            opt_state = state.opt_state
            if opt_offload is not None:
                # cpu_offload: stream host-pinned opt state onto the mesh for
                # the update, back to host after (XLA host-offload transfers).
                opt_state = jax.device_put(opt_state, opt_offload[0])
            updates, new_opt = tx.update(grads, opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_params = jax.tree.map(lambda n, o: jnp.where(finite, n, o), new_params, state.params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o) if hasattr(n, "shape") else n,
                new_opt,
                opt_state,
            )
            if opt_offload is not None:
                new_opt = jax.device_put(new_opt, opt_offload[1])
            new_scale = state.loss_scale.update(finite) if state.loss_scale is not None else None
            return state.replace(
                step=state.step + jnp.where(finite, 1, 0),
                params=new_params,
                opt_state=new_opt,
                loss_scale=new_scale,
            ), gnorm

        comm_hook = (
            getattr(self.ddp_handler, "comm_hook", "no")
            if self.ddp_handler is not None
            else "no"
        ) or "no"
        if comm_hook != "no":
            return self._comm_hook_step(
                loss_fn,
                slot=slot,
                comm_hook=comm_hook,
                policy=policy,
                num_accum=num_accum,
                update_fn=_update,
                donate=donate,
                has_aux=has_aux,
                mutable_state=mutable_state,
                grad_shardings=grad_shardings,
            )

        # SDC sentinel (sdc.py): when armed, every step's metrics carry a
        # cheap fused fingerprint of the new params + grad norm. Computed
        # INSIDE the jitted step so it folds into the one existing metrics
        # fetch, observed one step lagged like loss/grad_norm.
        _sdc_armed = (self.fault_tolerance is not None
                      and self.fault_tolerance.sdc is not None)

        def _maybe_digest(metrics, new_state, gnorm):
            if _sdc_armed:
                from .sdc import integrity_digest

                metrics["sdc_digest"] = integrity_digest(new_state.params, gnorm)
            return metrics

        if num_accum > 1:

            def step(state: TrainState, batch):
                batch = _microbatch_split(batch, num_accum)

                def body(carry, microbatch):
                    grads_acc, loss_acc, extra = carry
                    loss, _aux, new_extra, grads = _loss_and_grads(
                        state.params, extra, state.loss_scale, microbatch
                    )
                    return (
                        jax.tree.map(jnp.add, grads_acc, grads),
                        loss_acc + loss,
                        new_extra,
                    ), None

                zeros = jax.tree.map(lambda p: jnp.zeros_like(p), state.params)
                (grads, loss_sum, new_extra), _ = jax.lax.scan(
                    body, (zeros, jnp.asarray(0.0, jnp.float32), state.extra_state), batch
                )
                grads = jax.tree.map(lambda g: g / num_accum, grads)
                new_state, gnorm = _update(state, grads)
                if mutable_state:
                    new_state = new_state.replace(extra_state=new_extra)
                return new_state, _maybe_digest(
                    {"loss": loss_sum / num_accum, "grad_norm": gnorm},
                    new_state, gnorm)

        else:

            def step(state: TrainState, batch):
                loss, _aux, new_extra, grads = _loss_and_grads(
                    state.params, state.extra_state, state.loss_scale, batch
                )
                new_state, gnorm = _update(state, grads)
                if mutable_state:
                    new_state = new_state.replace(extra_state=new_extra)
                return new_state, _maybe_digest(
                    {"loss": loss, "grad_norm": gnorm}, new_state, gnorm)

        jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
        if self.compile_manager is not None:
            # Registers the underlying jitted step for executable counting
            # and AOT-warms every known manifest signature before step 0.
            self.compile_manager.register_step(jitted, slot=slot, label="train_step")

        def step_and_track(state: TrainState, batch):
            cm = self.compile_manager
            if cm is not None:
                cm.observe(batch)  # new signatures land in the shapes manifest
            if _sdc_armed:
                sdc = self.fault_tolerance.sdc
                if sdc.needs_golden:
                    # First prepared step: snapshot (state, batch) to host and
                    # pre-run the probe — it compiles the SAME executable the
                    # real steps use (identical shapes + shardings), so every
                    # later probe is recompile-free. Runs on restored copies:
                    # buffer donation never touches the live state.
                    sdc.capture_golden(jitted, state, batch)
            tel = self.telemetry
            if tel is None:
                new_state, metrics = jitted(state, batch)
                # Keep the accelerator's view current: with buffer donation
                # the previous state's arrays are dead after this call, so
                # save_state, Model.__call__ and trackers must see the new one.
                self._train_states[slot] = new_state
                return self._maybe_sentinel(new_state, metrics, slot), metrics
            if tel.profiler is not None:
                # One-time AOT cost_analysis capture (flops + bytes) BEFORE
                # the step call, while the pre-donation buffers are live —
                # the same slot sdc.capture_golden uses. Leaves the jit
                # dispatch cache untouched (flat-cache invariant).
                tel.profiler.capture_cost(jitted, state, batch)
            t0 = time.perf_counter()
            new_state, metrics = jitted(state, batch)
            if tel.handler.sync_timing:
                jax.block_until_ready(metrics)
            wall = time.perf_counter() - t0
            self._train_states[slot] = new_state
            tel.on_train_step(jitted, batch, wall, metrics=metrics)
            return self._maybe_sentinel(new_state, metrics, slot), metrics

        return step_and_track

    def _maybe_sentinel(self, new_state: TrainState, metrics, slot: int) -> TrainState:
        """Divergence-sentinel hook shared by every prepared-step wrapper:
        feeds the step metrics to fault tolerance (lagged host fetch — never
        stalls dispatch) and, when the sentinel rolled back, hands the
        RESTORED state back to the training loop in place of the diverged
        one (the loop's local ``state`` variable would otherwise keep
        training the garbage)."""
        ft = self.fault_tolerance
        if ft is None:
            return new_state
        restored = ft.observe_step(metrics, slot=slot)
        return restored if restored is not None else new_state

    def warmup_compile(self) -> Optional[dict]:
        """Compile every shapes-manifest signature against the prepared train
        steps NOW, off the training clock (compile_manager.py). Runs
        automatically inside :meth:`prepare_train_step` when a
        :class:`~accelerate_tpu.utils.CompileKwargs` handler enables warmup;
        call it again manually after the manifest grows (e.g. a fresh eval
        shape appeared). Idempotent — already-warmed signatures are skipped.
        Returns the cumulative warmup stats, or ``None`` when the compile
        manager is off."""
        if self.compile_manager is None:
            return None
        return self.compile_manager.warmup()

    def build_serving_engine(self, model, config: Optional[ServingConfig] = None,
                             disagg: Optional[DisaggConfig] = None, *,
                             chaos=None, tracing=None, journal=None):
        """Construct a :class:`~accelerate_tpu.serving.ServingEngine` over
        ``model`` (a prepared/loaded model with params on device), wired to
        this Accelerator's compile manager (prefill-chunk ladder, generation
        warmup) and telemetry recorder (serving block). ``config`` falls back
        to the :class:`~accelerate_tpu.utils.ServingConfig` handler passed at
        init; serving stays fully off — zero imports, zero hooks — without
        one.

        With a :class:`~accelerate_tpu.utils.DisaggConfig` — passed here or
        as a kwargs handler — the engine upgrades to the two-mesh
        :class:`~accelerate_tpu.disagg.DisaggServingEngine` (prefill and
        decode on planner-sized disjoint device slices, KV pages streamed
        between them). Disaggregation stays fully off without one.

        The Accelerator's fault-tolerance manager (when armed via
        :class:`~accelerate_tpu.utils.FaultToleranceKwargs`) is wired in
        too: a SIGTERM mid-serving triggers the engine's preemption drain
        (finish in-flight, shed the queue, report exit code 75).
        ``chaos`` takes a :class:`~accelerate_tpu.chaos.FaultInjector` for
        deterministic fault-injection runs. ``tracing`` takes a
        :class:`~accelerate_tpu.tracing.TraceRecorder`; it defaults to the
        recorder built from ``TelemetryKwargs(tracing=...)``, so most runs
        only set the kwarg and the engine picks it up through telemetry.
        ``journal`` takes a :class:`~accelerate_tpu.journal.RequestJournal`
        (or is built from ``ServingConfig.journal_dir``) to write-ahead-log
        every admission for exactly-once crash recovery (journal.py)."""
        cfg = config if config is not None else self.serving_config
        if cfg is None or not cfg.enabled:
            raise ValueError(
                "serving is off: pass ServingConfig(...) here or in "
                "Accelerator(kwargs_handlers=[...])."
            )
        dcfg = disagg if disagg is not None else self.disagg_config
        if dcfg is not None and dcfg.enabled:
            from .disagg import DisaggServingEngine

            return DisaggServingEngine(
                model, cfg, disagg=dcfg,
                compile_manager=self.compile_manager, telemetry=self.telemetry,
                fault_tolerance=self.fault_tolerance, chaos=chaos,
                tracing=tracing, journal=journal,
            )
        from .serving import ServingEngine

        return ServingEngine(
            model, cfg,
            compile_manager=self.compile_manager, telemetry=self.telemetry,
            fault_tolerance=self.fault_tolerance, chaos=chaos,
            tracing=tracing, journal=journal,
        )

    def build_fleet_router(self, cells, config=None, *, chaos=None,
                           tracing=None):
        """Construct a :class:`~accelerate_tpu.fleet.FleetRouter` over a
        registry of journaled serving cells (``{name: engine}`` or a list —
        each built via :meth:`build_serving_engine` with its OWN
        ``ServingConfig.journal_dir``), wired to this Accelerator's
        telemetry. The router adds the cell-granular robustness layer:
        session-affinity routing with load spillover, per-tick health
        classification, exactly-once cross-cell drain of a dead cell's
        journal, and whole-cell canary publish / scale (see
        :mod:`accelerate_tpu.fleet`). The fleet layer is OFF unless this
        router is built and ticked.

        ``config`` is a :class:`~accelerate_tpu.fleet.FleetConfig`;
        ``chaos`` takes a :class:`~accelerate_tpu.chaos.FaultInjector`
        (``cell_crash`` / ``cell_partition`` / ``router_heartbeat``
        points); ``tracing`` a
        :class:`~accelerate_tpu.tracing.TraceRecorder` for fleet spans and
        the ``accelerate_tpu_fleet_*`` gauge provider."""
        from .fleet import FleetRouter

        return FleetRouter(
            cells, config, chaos=chaos, telemetry=self.telemetry,
            tracing=tracing,
        )

    def build_weight_publisher(self, engine, config=None, *, chaos=None):
        """Construct a :class:`~accelerate_tpu.publish.WeightPublisher` that
        watches this (or another) run's checkpoint directory and hot-swaps
        verified weights into ``engine`` (a live
        :class:`~accelerate_tpu.serving.ServingEngine`) with zero downtime:
        only committed, hash-verified checkpoints are publishable, the
        train→serve topology gap is bridged through the resharding executor,
        and new versions roll out through a canary cohort with SLO
        auto-rollback (see :mod:`accelerate_tpu.publish`).

        ``config`` is a :class:`~accelerate_tpu.publish.PublishConfig`;
        ``chaos`` defaults to the engine's injector so a single seeded
        schedule covers serving and publication faults together."""
        from .publish import WeightPublisher

        if chaos is None:
            chaos = getattr(engine, "chaos", None)
        return WeightPublisher(
            engine, config, chaos=chaos, telemetry=self.telemetry,
        )

    def build_autoscale_controller(self, engine, config=None, *,
                                   device_pool=None, chaos=None):
        """Construct an
        :class:`~accelerate_tpu.autoscale.AutoscaleController` that closes
        the telemetry → planner → live-resize loop over ``engine`` (a
        :class:`~accelerate_tpu.disagg.DisaggServingEngine`): rolling-window
        SLO signals sampled every ``poll_ticks``, hysteresis + consecutive-
        breach + cooldown flap damping, a shared planner gate on every
        proposed topology, and zero-downtime grow/shrink/re-split through
        ``engine.resize`` (see :mod:`accelerate_tpu.autoscale`). Autoscaling
        is OFF unless this controller is built and polled.

        ``config`` is an :class:`~accelerate_tpu.autoscale.AutoscaleConfig`;
        ``device_pool`` is the device set the controller may scale across
        (defaults to the engine's current devices — no headroom);
        ``chaos`` defaults to the engine's injector so one seeded schedule
        covers serving, resize, and decision faults together."""
        from .autoscale import AutoscaleController

        if chaos is None:
            chaos = getattr(engine, "chaos", None)
        return AutoscaleController(
            engine, config, device_pool=device_pool, chaos=chaos,
            telemetry=self.telemetry,
        )

    def _comm_hook_step(
        self,
        loss_fn,
        *,
        slot: int,
        comm_hook: str,
        policy,
        num_accum: int,
        update_fn,
        donate: bool,
        has_aux: bool,
        mutable_state: bool,
        grad_shardings,
    ):
        """Build a train step whose DP gradient sync runs through a
        compression comm hook (``DistributedDataParallelKwargs.comm_hook``,
        reference: utils/dataclasses.py:157-241).

        GSPMD normally places the gradient ``psum`` itself, so to *replace*
        it the gradients are computed under ``shard_map`` over the DP axes
        (manual collectives) and reduced by
        :func:`parallel.comm_hooks.make_comm_hook_reducer` — fp16/bf16 wire
        compression or PowerSGD low-rank + error feedback. Hook state (the
        PowerSGD Q factors and error buffers) threads through the returned
        step in a host-side holder, one entry per prepared model slot.

        DDP semantics only: replicated params, pure data-parallel mesh.
        """
        from jax.sharding import PartitionSpec as P

        from .parallel.comm_hooks import init_powersgd_state, make_comm_hook_reducer

        if mutable_state or has_aux:
            raise NotImplementedError(
                "comm_hook is not supported together with mutable_state/has_aux"
            )
        if grad_shardings is not None:
            raise ValueError(
                "comm_hook requires replicated (DDP) gradients — it cannot "
                "compose with ZeRO-2 SHARD_GRAD_OP reduce-scatter"
            )
        mesh = self.mesh
        dp_axes = tuple(
            a for a in ("dp_replicate", "dp_shard") if mesh.shape.get(a, 1) > 1
        )
        bad = [
            a for a, s in mesh.shape.items()
            if a not in ("dp_replicate", "dp_shard") and s > 1
        ]
        if bad:
            raise ValueError(
                f"comm_hook requires a pure data-parallel mesh; axes {bad} have "
                "size > 1 (the reference's DDP comm hooks are DP-only too)"
            )
        params0 = self._train_states[slot].params
        for leaf in jax.tree.leaves(params0):
            spec = getattr(getattr(leaf, "sharding", None), "spec", None)
            if spec is not None and any(ax is not None for ax in spec):
                raise ValueError(
                    "comm_hook requires replicated (DDP) parameters; param "
                    f"sharded as {spec} — drop the FSDP plugin or the hook"
                )
        rank = int(getattr(self.ddp_handler, "powersgd_rank", 8))
        reducer = make_comm_hook_reducer(comm_hook, dp_axes, rank=rank)
        dp_total = 1
        for a in dp_axes:
            dp_total *= mesh.shape[a]
        if comm_hook == "powersgd":
            comm_state0 = init_powersgd_state(
                params0, rank, dp_size=dp_total, mesh=mesh, dp_axes=dp_axes
            )
        else:
            comm_state0 = jax.tree.map(lambda _: {}, params0)

        rep = lambda tree: jax.tree.map(  # noqa: E731 - local spec builder
            lambda x: P(*([None] * jnp.ndim(x))), tree
        )
        # Hook-state specs: Q factors are pmean'd (honestly replicated); the
        # error-feedback buffers are per-worker and SHARDED on their leading
        # dp axis (see init_powersgd_state's docstring for why a replicated
        # claim would be a silent-corruption hazard).
        _params_treedef = jax.tree_util.tree_structure(params0)
        _entries = _params_treedef.flatten_up_to(comm_state0)
        comm_specs = jax.tree_util.tree_unflatten(
            _params_treedef,
            [
                {}
                if not e
                else {
                    "q": P(None, None),
                    "e": P(dp_axes, None, None) if dp_axes else P(None, None, None),
                }
                for e in _entries
            ],
        )

        def hook_step(state: TrainState, batch, comm_state):
            loss_scale = state.loss_scale

            def local(params, batch, comm_state):
                def _fn(p, mb):
                    loss = loss_fn(policy.cast_for_compute(p), mb)
                    scale = loss_scale.scale if loss_scale is not None else 1.0
                    return (loss * scale).astype(jnp.float32), loss

                gfn = jax.value_and_grad(_fn, has_aux=True)
                if num_accum > 1:
                    micro = _microbatch_split(batch, num_accum, what="Per-device batch")

                    def body(carry, mb):
                        gacc, lacc = carry
                        (_, loss), g = gfn(params, mb)
                        return (jax.tree.map(jnp.add, gacc, g), lacc + loss), None

                    zeros = jax.tree.map(jnp.zeros_like, params)
                    (grads, loss_sum), _ = jax.lax.scan(
                        body, (zeros, jnp.asarray(0.0, jnp.float32)), micro
                    )
                    # DDP no_sync semantics: accumulate locally, reduce ONCE
                    # at the boundary — the hook fires once per optimizer
                    # step, exactly like the reference's bucket hooks.
                    grads = jax.tree.map(lambda g: g / num_accum, grads)
                    loss = loss_sum / num_accum
                else:
                    (_, loss), grads = gfn(params, batch)
                # PowerSGD reduces in TRUE gradient units: its error-feedback
                # buffers must not inherit the fp16 loss-scale factor (a
                # scale change would corrupt the carried residual by that
                # factor). fp16/bf16 wire hooks do the OPPOSITE — they
                # compress the still-scaled gradient, exactly like the
                # reference's fp16_compress_hook: the scale is what keeps
                # ~1e-6 grads above fp16's min normal on the wire.
                unscale = comm_hook == "powersgd"
                scale = loss_scale.scale if loss_scale is not None else None
                if unscale and scale is not None:
                    grads = jax.tree.map(lambda g: g / scale, grads)
                finite = grads_all_finite(grads)
                # The flag MUST agree across all DP workers: the reducer
                # pmean's P/Q, so one worker's inf grads make every worker's
                # new_comm NaN — a worker whose *local* grads were finite
                # would otherwise commit the poisoned (replicated-declared)
                # state and freeze the hook forever.
                for ax in dp_axes:
                    finite = jax.lax.pmin(finite.astype(jnp.int32), ax).astype(bool)
                grads, new_comm = reducer(grads, comm_state)
                # An overflowed step (inf grads -> NaN through qr) must not
                # poison the persistent hook state: keep the previous one.
                new_comm = jax.tree.map(
                    lambda n, o: jnp.where(finite, n, o), new_comm, comm_state
                )
                if unscale and scale is not None:
                    # update_fn unscales again — hand back scaled grads so the
                    # hooked and unhooked paths share one _update.
                    grads = jax.tree.map(lambda g: g * scale, grads)
                for ax in dp_axes:
                    loss = jax.lax.pmean(loss, ax)
                return loss, grads, new_comm

            batch_specs = jax.tree.map(
                lambda x: P(dp_axes, *([None] * (jnp.ndim(x) - 1)))
                if dp_axes
                else P(*([None] * jnp.ndim(x))),
                batch,
            )
            from .utils.environment import shard_map_compat

            loss, grads, new_comm = shard_map_compat(
                local,
                mesh=mesh,
                in_specs=(rep(state.params), batch_specs, comm_specs),
                out_specs=(P(), rep(state.params), comm_specs),
                check_vma=False,
            )(state.params, batch, comm_state)
            new_state, gnorm = update_fn(state, grads)
            return new_state, {"loss": loss, "grad_norm": gnorm}, new_comm

        # Donate the comm state too: the PowerSGD error buffers are
        # params-sized fp32 — updating them in place matters.
        jitted = jax.jit(hook_step, donate_argnums=(0, 2) if donate else ())
        holder = {"comm_state": comm_state0}
        if self.compile_manager is not None:
            # warmable=False: the hook step threads comm_state as a third
            # argument, which the manifest-driven warmup cannot synthesize.
            self.compile_manager.register_step(
                jitted, slot=slot, label="comm_hook_step", warmable=False
            )

        def step_and_track(state: TrainState, batch):
            cm = self.compile_manager
            if cm is not None:
                cm.observe(batch)
            tel = self.telemetry
            if tel is not None and tel.profiler is not None:
                # Same one-time cost capture as the fused path; the comm
                # hook threads its state as a third traced argument.
                tel.profiler.capture_cost(
                    jitted, state, batch, holder["comm_state"])
            t0 = time.perf_counter() if tel is not None else 0.0
            new_state, metrics, holder["comm_state"] = jitted(
                state, batch, holder["comm_state"]
            )
            self._train_states[slot] = new_state
            if tel is not None:
                if tel.handler.sync_timing:
                    jax.block_until_ready(metrics)
                tel.on_train_step(jitted, batch, time.perf_counter() - t0, metrics=metrics)
            return self._maybe_sentinel(new_state, metrics, slot), metrics

        return step_and_track

    # ------------------------------------------------------------------
    # Metrics & collectives surface (reference: accelerator.py:3000-3270)
    # ------------------------------------------------------------------

    def gather(self, tensor):
        return gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather across dp ranks and drop the duplicate tail samples that
        ``even_batches`` added on the last batch
        (reference: accelerator.py:3068-3140)."""
        try:
            recursively_apply(lambda x: x, input_data, error_on_other_type=True)
            all_tensors = True
        except TypeError:
            all_tensors = False
        if use_gather_object or not all_tensors:
            data = gather_object(input_data)
        else:
            data = self.gather(input_data)
        try:
            if self.gradient_state.end_of_dataloader and self.gradient_state.remainder > 0:
                def _adjust(tensor):
                    return tensor[: self.gradient_state.remainder]

                if all_tensors and not use_gather_object:
                    data = recursively_apply(_adjust, data)
                else:
                    data = data[: self.gradient_state.remainder]
        except (TypeError, IndexError, KeyError) as e:
            # Un-sliceable payloads keep the reference's forgiving contract,
            # but a real trimming bug must not vanish silently (VERDICT r2).
            # Strings only: warning_once dedups on its args' reprs, and a
            # live exception instance would defeat dedup AND pin its
            # traceback (and the gathered tensors it references) forever.
            logger.warning_once(
                "gather_for_metrics could not trim the duplicate tail samples "
                f"({type(e).__name__}: {e}); returning the untrimmed gather."
            )
        return data

    def reduce(self, tensor, reduction: str = "sum", scale: float = 1.0):
        return reduce(tensor, reduction, scale)

    def pad_across_processes(self, tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        return pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        return extract_model_from_parallel(model, keep_fp32_wrapper)

    # -- preemption observation (fault_tolerance.py) ----------------------

    def should_checkpoint(self) -> bool:
        """True once this process received a preemption signal
        (SIGTERM/SIGUSR1) and a final save should happen NOW. Local and
        free — poll it every step. On multi-host meshes where only some
        hosts get the signal, use :meth:`check_preemption` (collective)
        at a coarser cadence instead so the gang saves coherently."""
        ft = self.fault_tolerance
        return ft is not None and ft.preempted

    def check_preemption(self) -> bool:
        """Collective preemption poll: True on EVERY rank as soon as ANY
        rank received a preemption signal (one tiny allreduce — call it
        every step or every N steps). After the final ``save_state()``,
        exit with :attr:`preemption_exit_code` so the launch gang loop
        relaunches the run as resumable."""
        ft = self.fault_tolerance
        if ft is None:
            return False
        if self.num_processes <= 1:
            return ft.preempted
        return self.state.agree_any(ft.preempted)

    @property
    def preemption_exit_code(self) -> int:
        """Exit code a preemption-triggered shutdown should use
        (``utils.constants.PREEMPTION_EXIT_CODE``): the ``accelerate-tpu
        launch`` gang loop treats it as resumable and relaunches with
        ``ACCELERATE_RESTART_ATTEMPT`` bumped."""
        from .utils.constants import PREEMPTION_EXIT_CODE

        return PREEMPTION_EXIT_CODE

    # -- trigger sync (reference: accelerator.py:2852-2909) ---------------

    def set_trigger(self):
        self.flag_tensor = jnp.asarray(1, jnp.int32)

    def check_trigger(self) -> bool:
        if self.flag_tensor is None:
            self.flag_tensor = jnp.asarray(0, jnp.int32)
        flag = reduce(self.flag_tensor, reduction="sum")
        if int(np.asarray(flag)) >= 1:
            self.flag_tensor = jnp.asarray(0, jnp.int32)
            return True
        return False

    # ------------------------------------------------------------------
    # Autocast / profile contexts
    # ------------------------------------------------------------------

    @contextlib.contextmanager
    def autocast(self, autocast_handler=None):
        """Advisory on TPU: precision is a compile-time policy applied in the
        step builders; this context exists for API parity and casts eager ops
        via jax default dtype promotion (reference: accelerator.py:3410-3437)."""
        logger.warning_once(
            "Accelerator.autocast() is a no-op on TPU: mixed precision is a "
            "compile-time policy already applied inside prepared steps "
            "(mixed_precision=%s). Remove the context or keep it for API "
            "parity — behavior is identical either way.",
            self.state.mixed_precision,
        )
        yield

    @contextlib.contextmanager
    def profile(self, profile_handler: Optional[ProfileKwargs] = None):
        """jax.profiler trace honoring :class:`ProfileKwargs`
        (reference: accelerator.py:4202-4259 wraps torch.profiler).

        - ``schedule_option`` (wait/warmup/active/repeat/skip_first, torch
          semantics): yields a session whose ``.step()`` you call once per
          train step; traces cover only the active windows
          (``<dir>/cycle_<i>``).
        - ``profile_memory``: saves a device-memory profile next to each trace.
        - ``on_trace_ready``: called with the session after each trace closes.
        - ``record_shapes``/``with_stack``/``with_flops`` are inherent to XLA
          traces (shapes, source attribution and cost analysis are always in
          the XPlane data) — accepted for API parity.
        """
        from .utils.profiling import ProfileSession

        handler = profile_handler or self.profile_handler or ProfileKwargs()
        trace_dir = handler.output_trace_dir or (self.project_dir or ".")
        if handler.output_trace_dir is None and self.project_dir is None:
            yield None
            return
        session = ProfileSession(handler, trace_dir)
        session.enter()
        try:
            yield session
        finally:
            session.exit()

    # ------------------------------------------------------------------
    # Checkpointing & model export (reference: accelerator.py:3439-3748)
    # ------------------------------------------------------------------

    def register_for_checkpointing(self, *objects):
        invalid = [obj for obj in objects if not (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict"))]
        if invalid:
            raise ValueError(
                f"All `objects` must include a `state_dict` and `load_state_dict` function to be stored: {invalid}"
            )
        self._custom_objects.extend(objects)

    def register_save_state_pre_hook(self, hook: Callable):
        """``hook(models, weights, output_dir)`` runs before every
        ``save_state`` write (reference: accelerator.py:3856-3890). Here the
        hook receives ``(prepared_models, train_state, output_dir)``. Returns
        a removable handle (``.remove()``)."""
        self._save_state_pre_hooks.append(hook)
        return _HookHandle(self._save_state_pre_hooks, hook)

    def register_load_state_pre_hook(self, hook: Callable):
        """``hook(models, input_dir)`` runs before every ``load_state``
        restore (reference: accelerator.py:3892-3923)."""
        self._load_state_pre_hooks.append(hook)
        return _HookHandle(self._load_state_pre_hooks, hook)

    def save_state(self, output_dir: Optional[str] = None, safe_serialization: bool = True, block: bool = True, **save_model_func_kwargs):
        """``block=False`` + ``DISTRIBUTED_STATE_DICT``: the save returns as
        soon as device→host copies finish and bytes persist in a background
        thread while training continues (orbax async — the step's donated
        buffers are safe, the snapshot is already on host). Call
        :meth:`wait_for_checkpoint` (or ``end_training``) to drain; a second
        async save waits for the first. The reference has no async tier.

        With a :class:`~accelerate_tpu.utils.FaultToleranceKwargs` handler
        the save stages into ``<dir>.tmp``, commits atomically via
        manifest+rename, and transient storage failures retry with backoff
        (falling back to ``fallback_dir`` when configured)."""
        from .checkpointing import _checkpoint_dir, save_accelerator_state

        ft = self.fault_tolerance
        if ft is None:
            if self._save_state_pre_hooks:
                # Hooks see the RESOLVED target (automatic_checkpoint_naming
                # makes the raw arg None) so sidecar writers land next to the
                # checkpoint.
                resolved = _checkpoint_dir(self, output_dir)
                for hook in self._save_state_pre_hooks:
                    hook(self._models, self._train_state, resolved)
                output_dir = resolved
            return save_accelerator_state(
                self, output_dir, safe_serialization=safe_serialization, block=block
            )

        resolved = _checkpoint_dir(self, output_dir)

        def do_save(target: str) -> str:
            if self._save_state_pre_hooks:
                from .fault_tolerance import staging_path

                # Under atomic saves the hooks write into the STAGING dir so
                # their sidecar files are covered by the manifest and ride
                # the same commit; do_save re-runs them on every retry
                # attempt (the retry loop clears the staging dir between
                # attempts).
                hook_dir = staging_path(target) if ft.atomic else target
                if ft.atomic:
                    import shutil

                    if self.is_main_process and os.path.isdir(hook_dir):
                        shutil.rmtree(hook_dir)
                    self.wait_for_everyone()
                    os.makedirs(hook_dir, exist_ok=True)
                    # Tell save_accelerator_state this staging dir is live
                    # (hook sidecar files), not a stale leftover to wipe.
                    ft.prearm_staging(hook_dir)
                for hook in self._save_state_pre_hooks:
                    hook(self._models, self._train_state, hook_dir)
            return save_accelerator_state(
                self, target, safe_serialization=safe_serialization, block=block
            )

        return ft.run_save_with_retry(do_save, resolved)

    def wait_for_checkpoint(self):
        """Block until any in-flight async checkpoint finished persisting.
        A failure in orbax's background persist thread surfaces HERE (the
        save call itself already returned): the broken checkpointer is
        dropped so the next save starts fresh, the failure lands in
        telemetry, and a
        :class:`~accelerate_tpu.fault_tolerance.CheckpointSaveError` is
        raised instead of the error being silently swallowed."""
        ckptr = getattr(self, "_async_checkpointer", None)
        if ckptr is None:
            return
        try:
            ckptr.wait_until_finished()
            check = getattr(ckptr, "check_for_errors", None)
            if callable(check):
                check()
        except Exception as e:
            try:
                ckptr.close()
            except Exception:
                pass
            self._async_checkpointer = None
            if self.telemetry is not None:
                self.telemetry.record_event(
                    "checkpoint_async_error", error=f"{type(e).__name__}: {e}"[:500]
                )
            from .fault_tolerance import CheckpointSaveError

            raise CheckpointSaveError(
                f"async (orbax) checkpoint failed to persist in the "
                f"background: {e}"
            ) from e

    def _close_async_checkpointer(self):
        ckptr = getattr(self, "_async_checkpointer", None)
        if ckptr is not None:
            ckptr.wait_until_finished()
            ckptr.close()
            self._async_checkpointer = None

    def load_state(self, input_dir: Optional[str] = None, **load_model_func_kwargs):
        from .checkpointing import _checkpoint_dir, load_accelerator_state

        if self._load_state_pre_hooks:
            resolved = _checkpoint_dir(self, input_dir, for_load=True)
            for hook in self._load_state_pre_hooks:
                hook(self._models, resolved)
            input_dir = resolved
        return load_accelerator_state(self, input_dir)

    def migrate_plan(self, plan) -> dict:
        """Hot-swap the parallel layout mid-run (resharding.py).

        Reshards every prepared ``TrainState`` in place onto the mesh the new
        plan implies — leaves move through budget-bounded, donated
        ``device_put`` batches, so peak HBM stays within the
        :class:`~accelerate_tpu.utils.ElasticKwargs` staging budget. RNG,
        dataloader cursors, grad-accum state, loss scale and the step counter
        carry over untouched (they are replicated or host-side). The
        compile-manager's executables are invalidated — the old ones were
        specialized to the previous shardings — and re-warmed for the new
        shapes when ``warm_after_migrate`` is on.

        ``plan`` is a :class:`~accelerate_tpu.planner.ParallelPlan` or a
        :class:`~accelerate_tpu.parallelism_config.ParallelismConfig`.
        Requires an ElasticKwargs handler. Step functions built by
        ``prepare_train_step`` keep working (jit retraces for the new
        shardings), except ZeRO-2 (``SHARD_GRAD_OP``) and ``cpu_offload``
        setups, whose steps captured the old sharding constraints — rebuild
        those with ``prepare_train_step`` after migrating.

        Returns the reshard stats dict (also recorded as the telemetry
        ``reshard`` block)."""
        if self.elastic is None or not self.elastic.enabled:
            raise RuntimeError(
                "migrate_plan requires an ElasticKwargs handler: "
                "Accelerator(kwargs_handlers=[ElasticKwargs()])."
            )
        if not self._train_states:
            raise RuntimeError("Nothing prepared; call accelerator.prepare(...) first.")
        new_pc = (
            plan.to_parallelism_config() if hasattr(plan, "to_parallelism_config") else plan
        )
        if not isinstance(new_pc, ParallelismConfig):
            raise TypeError(
                f"migrate_plan takes a ParallelPlan or ParallelismConfig, got {type(plan)!r}"
            )
        # Pause point: drain any async checkpoint writer and let in-flight
        # steps retire before buffers start being donated out from under them.
        if hasattr(self, "wait_for_checkpoint"):
            self.wait_for_checkpoint()
        jax.block_until_ready(
            [s for st in self._train_states for s in jax.tree_util.tree_leaves(st)]
        )

        old_pc = self.state.parallelism_config
        new_pc = new_pc.infer_missing_axis(len(self.state.devices))
        self.state.parallelism_config = new_pc
        self.state._mesh = None  # the mesh property rebuilds from new_pc
        try:
            new_mesh = self.state.mesh
            executor = self.elastic.executor(new_mesh)
            for slot, st in enumerate(self._train_states):
                model = next(
                    (m for m in self._models if getattr(m, "_state_slot", None) == slot),
                    None,
                )
                if model is None:
                    continue
                param_shardings = plan_parameter_sharding(
                    st.params,
                    new_mesh,
                    fsdp_plugin=self.fsdp_plugin,
                    parallelism_config=new_pc,
                    tp_rules=model.tp_rules,
                )
                if st.tx is not None:
                    opt_shardings, grad_shardings, opt_offload = self._build_opt_shardings(
                        model, st.params, param_shardings, st.tx, new_pc
                    )
                else:
                    opt_shardings = ()
                    grad_shardings, opt_offload = None, None
                rep = replicated(new_mesh)
                state_shardings = TrainState(
                    step=rep,
                    params=param_shardings,
                    opt_state=opt_shardings,
                    extra_state=jax.tree.map(lambda _: rep, st.extra_state)
                    if st.extra_state
                    else st.extra_state,
                    accum_grads=None,
                    loss_scale=jax.tree.map(lambda _: rep, st.loss_scale)
                    if st.loss_scale is not None
                    else None,
                    apply_fn=st.apply_fn,
                    tx=st.tx,
                )
                # In-flight accumulation windows migrate with everything else
                # (grads follow the ZeRO-2 constraint when one is active).
                migrate_shardings = state_shardings
                if st.accum_grads is not None:
                    migrate_shardings = state_shardings.replace(
                        accum_grads=grad_shardings or param_shardings
                    )
                new_state = executor.put_tree(
                    st, migrate_shardings, prefix=f"slot{slot}"
                )
                self._train_states[slot] = new_state
                self._slot_meta[slot] = {
                    "state_shardings": state_shardings,
                    "param_shardings": param_shardings,
                    "grad_shardings": grad_shardings,
                    "opt_offload": opt_offload,
                }
                if slot == 0:
                    self._state_shardings = state_shardings
                    self._param_shardings = param_shardings
                    self._grad_shardings = grad_shardings
                    self._opt_offload = opt_offload
        except Exception:
            # Roll the topology back so a failed migration leaves a
            # consistent (old) mesh behind; state leaves are untouched until
            # the executor runs, and put_tree only commits whole trees.
            self.state.parallelism_config = old_pc
            self.state._mesh = None
            raise
        # Jitted-step caches are stale: old executables were compiled for the
        # previous shardings (and donation layout).
        self._grad_fn_cache.clear()
        self._apply_jit = None
        self._gradnorm_jit = None
        if plan is not None and hasattr(plan, "to_parallelism_config"):
            self.active_plan = plan
            if self.telemetry is not None:
                self.telemetry.note_plan(plan.to_json_dict(), None)
            if self.compile_manager is not None:
                self.compile_manager.note_plan(plan)
        if self.compile_manager is not None:
            dropped = self.compile_manager.invalidate_steps()
            logger.info(
                "migrate_plan: dropped %d stale executable(s).", dropped,
                main_process_only=True,
            )
            if getattr(self.elastic.handler, "warm_after_migrate", True):
                self.compile_manager.warmup()
        stats = executor.stats()
        self.elastic.note_reshard(stats, kind="migrate")
        from .planner import _layout_dict, layout_str

        logger.info(
            "migrate_plan: %s -> %s (%d leaves, %s bytes, depth %d, %.3fs).",
            layout_str(_layout_dict(old_pc)) if old_pc is not None else "default",
            layout_str(_layout_dict(new_pc)),
            stats.get("moved_leaves", 0),
            f"{stats.get('bytes_transferred', 0):,}",
            stats.get("depth", 0),
            stats.get("wall_s", 0.0),
            main_process_only=True,
        )
        return stats

    def unscale_gradients(self, optimizer=None):
        """Parity advisory (reference: accelerator.py:2928-2944 unscales the
        GradScaler before manual grad inspection): fp16 loss-scale handling
        here is fused into the step — grads exposed via ``optimizer.grads`` /
        ``train_state.accum_grads`` are ALREADY unscaled, so there is nothing
        to do. Kept so migrating call sites run unchanged."""
        return None

    def save_model(
        self,
        model: Model,
        save_directory: str,
        max_shard_size: Union[int, str] = "5GB",
        safe_serialization: bool = True,
    ):
        """Export params as (sharded) safetensors + index
        (reference: accelerator.py:3439-3551)."""
        params = to_global_host(model.params)
        flat = flatten_state_dict(params)
        if self.is_main_process:
            save_sharded_safetensors(flat, save_directory, max_shard_size=max_shard_size)
        self.wait_for_everyone()

    def save(self, obj, f, safe_serialization: bool = False):
        from .utils.operations import save as _save

        _save(obj, f, save_on_each_node=self.project_configuration.save_on_each_node,
              safe_serialization=safe_serialization)

    def get_state_dict(self, model: Model, unwrap: bool = True):
        return flatten_state_dict(to_global_host(model.params))

    # ------------------------------------------------------------------
    # Tracking (reference: accelerator.py:3271-3408)
    # ------------------------------------------------------------------

    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: dict = {}):
        from .tracking import resolve_trackers

        self.trackers = resolve_trackers(
            self.log_with, project_name, self.logging_dir, init_kwargs
        )
        if config is not None:
            for tracker in self.trackers:
                tracker.store_init_configuration(config)

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"{name} is not an available tracker stored inside the `Accelerator`.")

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: dict = {}):
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log(values, step=step, **log_kwargs.get(tracker.name, {}))

    def end_training(self):
        self._close_async_checkpointer()
        if self.fault_tolerance is not None:
            self.fault_tolerance.close()  # drain/restore signal handlers
        if self.telemetry is not None:
            self.telemetry.close()  # summary still sees the compile manager
        if self.compile_manager is not None:
            self.compile_manager.close()  # persistent-cache LRU prune
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.finish()
        self.wait_for_everyone()

    # ------------------------------------------------------------------
    # Memory / teardown (reference: accelerator.py:4260-4359)
    # ------------------------------------------------------------------

    def free_memory(self, *objects):
        from .utils.memory import release_memory

        self._close_async_checkpointer()
        if self.fault_tolerance is not None:
            self.fault_tolerance.close()
            self.fault_tolerance = None
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
        if self.compile_manager is not None:
            self.compile_manager.close()
            self.compile_manager = None
        self._train_state = None
        self._state_shardings = None
        self._grad_shardings = None
        self._param_shardings = None
        self._opt_offload = None
        self._grad_fn_cache.clear()
        self._apply_jit = None
        self._gradnorm_jit = None
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self.step = 0
        return release_memory(*objects)

    def clear(self, *objects):
        return self.free_memory(*objects)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches)

    def verify_device_map(self, model) -> bool:
        """True when ``model`` was dispatched with a multi-placement device
        map (reference: accelerator.py:3744-3760 checks for hf_device_map —
        such models must not also be prepared for distributed training)."""
        from .big_modeling import DispatchedModel

        if not isinstance(model, DispatchedModel):
            return False
        placements = {str(p) for p in model.device_map.values()}
        return len(placements) > 1

    def __deepcopy__(self, memo):
        return self
