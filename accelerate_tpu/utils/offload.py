"""Disk offload store: numpy memmaps + a json index.

Same on-disk contract as the reference (utils/offload.py:25-213): one
``<name>.dat`` raw memmap per weight plus ``index.json`` carrying shape and
dtype, so offloaded weights can be mapped back lazily with O(1) host memory.
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from typing import Any, Optional

import numpy as np


def offload_weight(weight: np.ndarray, weight_name: str, offload_folder: str, index: Optional[dict] = None) -> dict:
    """Write one weight to ``<folder>/<name>.dat``; return its index entry
    (reference: utils/offload.py:25-47)."""
    os.makedirs(offload_folder, exist_ok=True)
    dtype = np.dtype(weight.dtype)
    entry = {"dtype": dtype.name, "shape": list(weight.shape)}
    path = os.path.join(offload_folder, f"{weight_name.replace('/', '--')}.dat")
    shape = tuple(weight.shape) or (1,)
    mm = np.memmap(path, dtype=dtype, mode="w+", shape=shape)
    mm[:] = np.asarray(weight).reshape(shape)[:]
    mm.flush()
    if index is not None:
        index[weight_name] = entry
    return entry


def load_offloaded_weight(offload_folder: str, weight_name: str, weight_info: Mapping[str, Any]) -> np.ndarray:
    """Memmap one weight back (reference: utils/offload.py:50-68)."""
    path = os.path.join(offload_folder, f"{weight_name.replace('/', '--')}.dat")
    shape = tuple(weight_info["shape"]) or (1,)
    mm = np.memmap(path, dtype=np.dtype(weight_info["dtype"]), mode="r", shape=shape)
    if not weight_info["shape"]:
        return np.asarray(mm[0])
    return mm


def save_offload_index(index: Mapping[str, Any], offload_folder: str):
    os.makedirs(offload_folder, exist_ok=True)
    path = os.path.join(offload_folder, "index.json")
    current = {}
    if os.path.isfile(path):
        with open(path) as f:
            current = json.load(f)
    current.update(index)
    with open(path, "w") as f:
        json.dump(current, f, indent=2)


def load_offload_index(offload_folder: str) -> dict:
    path = os.path.join(offload_folder, "index.json")
    if not os.path.isfile(path):
        return {}
    with open(path) as f:
        return json.load(f)


def offload_state_dict(save_dir: str, state_dict: Mapping[str, np.ndarray]) -> dict:
    """Offload a whole flat state dict; returns the index
    (reference: utils/offload.py:71-95)."""
    index: dict = {}
    for name, w in state_dict.items():
        offload_weight(np.asarray(w), name, save_dir, index=index)
    save_offload_index(index, save_dir)
    return index


class OffloadedWeightsLoader(Mapping):
    """Lazy read-through Mapping over {in-memory state dict} ∪ {offload dir}
    (reference: utils/offload.py:98-168)."""

    def __init__(
        self,
        state_dict: Optional[Mapping[str, np.ndarray]] = None,
        save_folder: Optional[str] = None,
        index: Optional[Mapping[str, Any]] = None,
    ):
        if state_dict is None and save_folder is None:
            raise ValueError("Need either a state_dict or a save_folder")
        self.state_dict = dict(state_dict or {})
        self.save_folder = save_folder
        self.index = dict(index if index is not None else (load_offload_index(save_folder) if save_folder else {}))
        self.all_keys = sorted(set(self.state_dict) | set(self.index))

    def __getitem__(self, key: str) -> np.ndarray:
        if key in self.state_dict:
            return self.state_dict[key]
        return load_offloaded_weight(self.save_folder, key, self.index[key])

    def __iter__(self):
        return iter(self.all_keys)

    def __len__(self):
        return len(self.all_keys)


def extract_submodule_tensors(loader: Mapping, prefixes: list[str], sep: str = "/") -> dict:
    """Sub-view of a weights mapping per module prefix
    (the ``extract_submodules_state_dict`` role, utils/offload.py:171-213)."""
    out = {}
    for key in loader:
        if any(key == p or key.startswith(p + sep) for p in prefixes):
            out[key] = loader[key]
    return out
