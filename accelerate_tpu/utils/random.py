"""Seeding and cross-process RNG synchronization.

Reference: src/accelerate/utils/random.py:40-165. Torch RNG is stateful and
must be broadcast between ranks; JAX PRNG is functional, which makes sync
trivial — we keep a small named-stream registry (the moral equivalent of
torch's generator objects) and broadcast the key from rank 0 when asked.
"""

from __future__ import annotations

import enum
import os
import random
from typing import Iterable, Optional

import jax
import numpy as np


class RNGType(str, enum.Enum):
    JAX = "jax"
    NUMPY = "numpy"
    PYTHON = "python"
    GENERATOR = "generator"  # alias of JAX for API parity with the reference


class _KeyRegistry:
    """Named functional PRNG streams ("params", "dropout", "sampler", ...).

    ``fold_in``-based: consuming a key advances the stream deterministically,
    so checkpoint/resume only needs (seed, counter) pairs.
    """

    def __init__(self):
        self._seed: int = 0
        self._counters: dict[str, int] = {}

    def seed(self, seed: int):
        self._seed = int(seed)
        self._counters = {}

    def next_key(self, stream: str = "default") -> jax.Array:
        import zlib

        count = self._counters.get(stream, 0)
        self._counters[stream] = count + 1
        key = jax.random.key(self._seed)
        # crc32, not hash(): python string hashing is randomized per process
        # (PYTHONHASHSEED), which would give each host a different stream.
        key = jax.random.fold_in(key, zlib.crc32(stream.encode()) % (2**31))
        return jax.random.fold_in(key, count)

    def peek_state(self) -> dict:
        return {"seed": self._seed, "counters": dict(self._counters)}

    def restore_state(self, state: dict):
        self._seed = int(state["seed"])
        self._counters = dict(state["counters"])


_REGISTRY = _KeyRegistry()


def set_seed(seed: int, device_specific: bool = False, deterministic: bool = False):
    """Seed python, numpy and the JAX key registry in one call
    (reference: utils/random.py:40-86). ``device_specific`` offsets the seed
    by process index so each host draws different data-augmentation noise."""
    from ..state import PartialState

    if device_specific:
        seed += PartialState().process_index
    random.seed(seed)
    np.random.seed(seed % (2**32))
    _REGISTRY.seed(seed)
    os.environ["ACCELERATE_SEED"] = str(seed)
    return seed


def next_rng_key(stream: str = "default") -> jax.Array:
    """Draw the next key from a named stream."""
    return _REGISTRY.next_key(stream)


def rng_state() -> dict:
    """Snapshot all host RNG state for checkpointing
    (reference: checkpointing.py:154-179)."""
    return {
        "python": random.getstate(),
        "numpy": np.random.get_state(),
        "jax": _REGISTRY.peek_state(),
    }


def load_rng_state(state: dict):
    random.setstate(state["python"])
    np.random.set_state(state["numpy"])
    _REGISTRY.restore_state(state["jax"])


def synchronize_rng_state(rng_type: Optional[RNGType] = None, generator=None):
    """Broadcast rank-0's RNG state of one kind to all processes
    (reference: utils/random.py:88-130)."""
    from ..state import PartialState
    from .operations import broadcast_object_list

    state = PartialState()
    if state.num_processes == 1:
        return
    if rng_type in (RNGType.JAX, RNGType.GENERATOR, None):
        payload = [_REGISTRY.peek_state()]
        broadcast_object_list(payload, from_process=0)
        _REGISTRY.restore_state(payload[0])
    if rng_type == RNGType.NUMPY:
        payload = [np.random.get_state()]
        broadcast_object_list(payload, from_process=0)
        np.random.set_state(payload[0])
    if rng_type == RNGType.PYTHON:
        payload = [random.getstate()]
        broadcast_object_list(payload, from_process=0)
        random.setstate(payload[0])


def synchronize_rng_states(rng_types: Iterable[str], generator=None):
    for rng_type in rng_types:
        synchronize_rng_state(RNGType(rng_type), generator=generator)
