"""Plugin dataclasses & kwargs handlers (layer L2).

Re-design of the reference's ``utils/dataclasses.py`` (3226 LoC of torch
plugin plumbing, reference: src/accelerate/utils/dataclasses.py). The torch
backend zoo (DDP kwargs, FSDP plugin, DeepSpeed plugin, Megatron plugin)
collapses on TPU into *sharding and precision choices* consumed by the
Accelerator when it builds mesh + PartitionSpecs + the jitted step. We keep
the reference's config surface (field names, env-var decode) so launch
configs translate, but each plugin's payload is a JAX-native policy.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import functools
import os
import warnings
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Optional

import jax.numpy as jnp

from .environment import parse_choice_from_env, parse_flag_from_env, str_to_bool


class KwargsHandler:
    """Base: ``to_kwargs()`` returns the diff vs default values
    (reference: utils/dataclasses.py:70-89)."""

    def to_dict(self):
        return copy.deepcopy(self.__dict__)

    def to_kwargs(self):
        default_dict = self.__class__().to_dict()
        this_dict = self.to_dict()
        return {k: v for k, v in this_dict.items() if default_dict[k] != v}


class EnumWithContains(enum.EnumMeta):
    def __contains__(cls, item):
        try:
            cls(item)
        except ValueError:
            return False
        return True


class BaseEnum(str, enum.Enum, metaclass=EnumWithContains):
    def __str__(self):
        return self.value

    @classmethod
    def list(cls):
        return list(map(str, cls))


class PrecisionType(BaseEnum):
    NO = "no"
    BF16 = "bf16"
    FP16 = "fp16"
    FP8 = "fp8"


class ComputeEnvironment(BaseEnum):
    LOCAL_MACHINE = "LOCAL_MACHINE"
    TPU_POD = "TPU_POD"


class LoggerType(BaseEnum):
    """Tracker identifiers accepted by ``Accelerator(log_with=...)``
    (reference: utils/dataclasses.py LoggerType). Plain strings work too —
    ``filter_trackers`` (tracking.py) resolves either."""

    ALL = "all"
    TENSORBOARD = "tensorboard"
    WANDB = "wandb"
    MLFLOW = "mlflow"
    COMETML = "comet_ml"
    AIM = "aim"
    CLEARML = "clearml"
    DVCLIVE = "dvclive"
    SWANLAB = "swanlab"
    TRACKIO = "trackio"


class SaveFormat(BaseEnum):
    SAFETENSORS = "safetensors"
    ORBAX = "orbax"
    MSGPACK = "msgpack"


DTYPE_MAP = {
    "no": jnp.float32,
    "fp32": jnp.float32,
    "bf16": jnp.bfloat16,
    "fp16": jnp.float16,
    "fp8": jnp.float8_e4m3fn,
}


@dataclass
class MixedPrecisionPolicy(KwargsHandler):
    """What dtype each tensor class uses inside the jitted step.

    TPU-native replacement for torch autocast + GradScaler + FSDP
    MixedPrecisionPolicy (reference: accelerator.py:561-612,
    utils/fsdp_utils.py:861-870). Params and optimizer state stay fp32 master
    copies; compute and activations run in ``compute_dtype``; gradients are
    reduced in ``reduce_dtype``.
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    reduce_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    @classmethod
    def from_mixed_precision(cls, mixed_precision: str) -> "MixedPrecisionPolicy":
        if mixed_precision in (None, "no"):
            return cls(compute_dtype=jnp.float32)
        if mixed_precision == "bf16":
            return cls(compute_dtype=jnp.bfloat16)
        if mixed_precision == "fp16":
            # fp16 on TPU still reduces in fp32; dynamic loss scaling is
            # handled by the step builder when fp16 is requested.
            return cls(compute_dtype=jnp.float16)
        if mixed_precision == "fp8":
            return cls(compute_dtype=jnp.bfloat16)  # fp8 applies per-matmul via recipe
        raise ValueError(f"Unknown mixed precision {mixed_precision}")

    def cast_for_compute(self, tree):
        import jax

        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """(reference: utils/dataclasses.py:1120-1160)"""

    num_steps: int = None
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False


@dataclass
class GradScalerKwargs(KwargsHandler):
    """Dynamic loss-scaling config for fp16 (reference:
    utils/dataclasses.py:242-270). On TPU bf16 needs no scaling; this exists
    for fp16 parity and is implemented in pure JAX inside the step."""

    init_scale: float = 65536.0
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    enabled: bool = True


@dataclass
class InitProcessGroupKwargs(KwargsHandler):
    """(reference: utils/dataclasses.py:272-310) — maps to
    jax.distributed.initialize timeouts."""

    backend: Optional[str] = "xla"
    init_method: Optional[str] = None
    timeout: Optional[timedelta] = None


@dataclass
class DistributedDataParallelKwargs(KwargsHandler):
    """Reference: utils/dataclasses.py:157-241. Under GSPMD there is no DDP
    reducer to configure — gradient mean is a single psum the compiler
    schedules — so the bucketing knobs are advisory no-ops. ``comm_hook``
    IS live: it routes the step through a ``shard_map``-controlled gradient
    sync (parallel/comm_hooks.py) replacing the psum with fp16/bf16 wire
    compression or PowerSGD rank-``powersgd_rank`` low-rank reduction with
    error feedback — for DCN-spanning data-parallel meshes where the grad
    all-reduce can't hide behind compute. DDP (replicated-param) meshes
    only; pass via ``Accelerator(kwargs_handlers=[...])``."""

    bucket_cap_mb: int = 25
    find_unused_parameters: bool = False
    gradient_as_bucket_view: bool = False
    static_graph: bool = False
    comm_hook: str = "no"  # no | fp16 | bf16 | powersgd
    powersgd_rank: int = 8  # reference: matrix_approximation_rank state option


@dataclass
class AutocastKwargs(KwargsHandler):
    """(reference: utils/dataclasses.py:311-340)"""

    enabled: bool = True
    cache_enabled: bool = None


class FP8Format(BaseEnum):
    E4M3 = "E4M3"
    E5M2 = "E5M2"
    HYBRID = "HYBRID"


@dataclass
class FP8RecipeKwargs(KwargsHandler):
    """fp8 matmul recipe (reference: TERecipeKwargs/AORecipeKwargs,
    utils/dataclasses.py:312-484). On TPU this selects XLA float8 dots:
    activations/weights quantized per-tensor with delayed or current scaling,
    master weights bf16/fp32.

    ``backend`` mirrors the reference's AO→TE→MSAMP auto-pick
    (reference: accelerator.py:478-503): "TE" and "AO" both map to the
    native float8-operand dot path (ops/fp8.py ``_f8_dot`` — TE's HYBRID
    GEMM recipe and torchao's dynamic-scaling Float8Linear are the same
    computation under XLA), "QDQ" forces the quantize-dequantize
    formulation, and "AUTO" lets the platform decide. "MSAMP" raises:
    MS-AMP is deprecated upstream and deliberately dropped here (see
    COVERAGE.md, deliberate drops)."""

    fp8_format: str = "HYBRID"  # E4M3 fwd / E5M2 bwd when HYBRID
    backend: str = "AUTO"       # AUTO | TE | AO | QDQ (MSAMP: rejected)
    amax_history_len: int = 16
    amax_compute_algo: str = "max"
    margin: int = 0
    use_during_eval: bool = False

    def __post_init__(self):
        self.fp8_format = self.fp8_format.upper()
        if self.fp8_format not in FP8Format.list():
            raise ValueError(f"fp8_format must be one of {FP8Format.list()}")
        self.backend = self.backend.upper()
        from ..ops.fp8 import backend_to_native

        backend_to_native(self.backend)  # validates (MSAMP rejected here)

    @property
    def native_dots(self) -> "bool | None":
        """None = platform default (ACCELERATE_FP8_NATIVE env)."""
        from ..ops.fp8 import backend_to_native

        return backend_to_native(self.backend)


@dataclass
class ProfileKwargs(KwargsHandler):
    """jax.profiler configuration (reference: utils/dataclasses.py:486-601
    wraps torch.profiler)."""

    activities: Optional[list] = None
    schedule_option: Optional[dict] = None
    on_trace_ready: Optional[Callable] = None
    record_shapes: bool = False
    profile_memory: bool = False
    with_stack: bool = False
    with_flops: bool = False
    output_trace_dir: Optional[str] = None


@dataclass
class TelemetryKwargs(KwargsHandler):
    """Step-level telemetry config (telemetry.py). Passing this handler to
    ``Accelerator(kwargs_handlers=[...])`` turns the subsystem on; without it
    no recorder exists and every hook is a single ``None`` check.

    - ``sync_timing``: block on the step's metrics before stopping the step
      timer. Exact per-step device wall time, but it defeats async dispatch —
      leave False (dispatch wall; converges to the true step time once the
      device queue applies backpressure) for production loops.
    - ``log_every``: forward the smoothed summary into the tracker stack via
      ``Accelerator.log()`` every N steps (main process; 0 disables).
    - ``straggler_probe_every``: allgather step times across ranks every N
      steps and record max/min skew (0 disables).
    - ``memory_every``: sample device-memory stats every N steps (some
      backends make ``memory_stats()`` a sync point).
    - ``output_dir``: JSONL destination; default ``<project_dir>/telemetry``.
    - ``max_log_bytes``: size-triggered rotation bound for the per-rank
      JSONL — when the live file crosses it, it is renamed to
      ``<name>.jsonl.1`` (replacing any previous rotation) and a fresh
      file starts, with a one-time warning. Generous but finite by
      default; ``None``/0 disables rotation.
    - ``tracing``: request-scoped tracing (tracing.py). ``True`` (default
      recorder), a dict of :class:`~accelerate_tpu.tracing.TraceConfig`
      field overrides, or a ``TraceConfig``. The recorder lands on
      ``telemetry.tracing``, serving engines built through the
      accelerator inherit it, and ``summary()`` gains a ``"tracing"``
      block. Off (None) means zero cost: every hook is one ``is None``
      check.
    - ``profile``: device-time attribution (profiler.py). ``True``
      (default :class:`~accelerate_tpu.profiler.ProfilerConfig`), a dict
      of field overrides, or a ``ProfilerConfig``. The profiler lands on
      ``telemetry.profiler``, ``summary()`` gains a ``"profile"`` block
      (exactly-summing per-step terms, comm/compute overlap ratio,
      BandwidthTable residuals), and abnormal exits dump its flight ring
      as ``flight_<exit_class>.json``. Attribution is lagged one step —
      zero extra device syncs; off (None) is the same zero-cost contract
      as ``tracing``.
    """

    enabled: bool = True
    sync_timing: bool = False
    log_every: int = 10
    straggler_probe_every: int = 50
    straggler_warn_skew: float = 0.2
    ema_alpha: float = 0.1
    memory_every: int = 1
    output_dir: Optional[str] = None
    max_log_bytes: Optional[int] = 256 * 1024 * 1024
    tracing: Any = None
    profile: Any = None


@dataclass
class FaultToleranceKwargs(KwargsHandler):
    """Fault-tolerance config (fault_tolerance.py). Passing this handler to
    ``Accelerator(kwargs_handlers=[...])`` turns the subsystem on; without it
    ``accelerator.fault_tolerance`` is ``None``, every hook site is a single
    ``None`` check, and the checkpoint byte layout is unchanged.

    Four pillars (docs/usage_guides/fault_tolerance.md):

    - **Atomic verified checkpoints**: every save writes into a
      ``checkpoint_N.tmp`` staging dir, fsyncs, emits a ``manifest.json``
      (per-file sizes + checksums + world size + step) and renames to
      ``checkpoint_N`` as the commit point. ``load_state()`` walks
      newest→oldest and restores the newest checkpoint whose manifest
      verifies, skipping torn ones. ``total_limit`` pruning runs *after* the
      commit, so a failed save can never destroy the only good checkpoint.
      ``checksum``: ``"sha256"`` hashes every byte; ``"size"`` checks
      existence + size only (for multi-TB checkpoints where hashing
      dominates save time).
    - **Preemption-aware auto-save**: SIGTERM/SIGUSR1 handlers installed at
      ``prepare()`` set a flag the training loop observes via
      ``accelerator.should_checkpoint()`` (local, free) or
      ``accelerator.check_preemption()`` (collective — rank-coherent on
      multi-host meshes). After the final save, exit with
      ``utils.constants.PREEMPTION_EXIT_CODE`` — the launch gang loop treats
      it as resumable and relaunches with ``ACCELERATE_RESTART_ATTEMPT`` set
      so elastic auto-resume continues the run.
    - **Save retry**: transient storage errors (OSError / TensorStore
      failures) retry ``save_retries`` times with jittered exponential
      backoff (``retry_backoff_s`` doubling up to ``retry_backoff_max_s``)
      before falling back to ``fallback_dir`` when configured.
    - **Divergence sentinel**: watches the step metrics (loss + grad norm,
      fetched one step lagged so the watch never stalls async dispatch) for
      ``sentinel_window`` consecutive nonfinite or exploding
      (> ``sentinel_explode_factor`` × EMA) steps. Policy ``"warn"`` logs +
      records the episode, ``"halt"`` raises :class:`DivergenceError`,
      ``"rollback"`` restores the newest *verified* checkpoint (at most
      ``max_rollbacks`` times) and re-primes RNG/dataloader state so the run
      resumes deterministically. ``"off"`` disables the watch entirely.

    Two more pillars ride on the same manager (default off):

    - **Chaos injection** (``chaos``): a
      :class:`~accelerate_tpu.chaos.FaultInjector` (or its constructor
      kwargs as a dict) drives deterministic training-side faults —
      ``train_step``/``nonfinite_grad``/``slow_step``,
      ``checkpoint_save``/``torn_write``, ``dataloader_batch``/
      ``corrupt_batch``, ``host_heartbeat``/``dead_host`` — through the
      SAME recovery paths real failures take (sentinel → rollback, save
      retry → fallback, exit → gang relaunch). ``None`` (default) keeps
      every hook a single ``None`` check.
    - **SDC sentinel** (``sdc``): an
      :class:`~accelerate_tpu.sdc.SDCConfig` (or its constructor kwargs as
      a dict) arms the silent-data-corruption defenses — every step
      fingerprints the new params + grad norm inside the jitted step (one
      fused reduction riding the existing metrics fetch, one step lagged),
      every ``vote_every`` steps the dp replicas allgather and
      majority-vote the digests bit-wise, and a mismatch triggers the
      redundant-compute probe on a golden batch to classify *transient*
      (repair in place: rollback or majority broadcast) vs *sticky* (bad
      silicon: quarantine the host on disk, exit
      ``utils.constants.SDC_EXIT_CODE`` so the supervisor relaunches the
      gang SHRUNK without it). Independent of the divergence ``sentinel``
      policy — SDC is finite-but-wrong, invisible to nonfinite checks.
      ``None`` (default) keeps every hook a single ``None`` check.
    - **Step watchdog** (``watchdog``): a host-side thread + lagged
      per-step notes detecting a progress-free or straggling gang. A step
      older than ``watchdog_warn_s`` emits a ``training_stalled`` telemetry
      event (per-rank last-step ages, straggler named); past
      ``watchdog_stall_s`` the policy escalates — ``"warn"`` keeps logging,
      ``"error"`` raises :class:`~accelerate_tpu.fault_tolerance.
      TrainingStalledError` at the next completed step, ``"preempt"``
      self-preempts (SIGTERM → preemption save if the loop is alive, then
      hard-exits ``TRAINING_STALLED_EXIT_CODE`` after a grace period so the
      supervisor relaunches from the newest verified checkpoint). With
      ``watchdog_heartbeat_every`` > 0 and a multi-process gang, every N
      steps the ranks allgather (step, age) over the ``agree_any``-style
      channel so a stalled PEER is detected and named too.

    All events (save retries, torn checkpoints skipped, preemption saves,
    rollbacks, injected faults, stall warnings) flow into the telemetry
    JSONL when a :class:`TelemetryKwargs` handler is also present.
    """

    enabled: bool = True
    atomic_checkpoints: bool = True
    verify_on_load: bool = True
    checksum: str = "sha256"  # sha256 | size
    save_retries: int = 3
    retry_backoff_s: float = 0.5
    retry_backoff_max_s: float = 8.0
    fallback_dir: Optional[str] = None
    install_signal_handlers: bool = True
    preemption_signals: tuple = ("SIGTERM", "SIGUSR1")
    sentinel: str = "warn"  # off | warn | halt | rollback
    sentinel_window: int = 3
    sentinel_explode_factor: float = 10.0
    sentinel_ema_alpha: float = 0.1
    max_rollbacks: int = 2
    chaos: Optional[object] = None  # FaultInjector | dict of its kwargs
    sdc: Optional[object] = None  # sdc.SDCConfig | dict of its kwargs
    watchdog: str = "off"  # off | warn | error | preempt
    watchdog_warn_s: float = 60.0
    watchdog_stall_s: float = 300.0
    watchdog_poll_s: float = 1.0
    watchdog_heartbeat_every: int = 0  # steps between gang heartbeats (0 off)
    watchdog_grace_s: float = 30.0  # preempt policy: SIGTERM → hard-exit gap

    def __post_init__(self):
        if self.checksum not in ("sha256", "size"):
            raise ValueError("checksum must be sha256|size")
        if self.sentinel not in ("off", "warn", "halt", "rollback"):
            raise ValueError("sentinel must be off|warn|halt|rollback")
        if self.sentinel_window < 1:
            raise ValueError("sentinel_window must be >= 1")
        if self.watchdog not in ("off", "warn", "error", "preempt"):
            raise ValueError("watchdog must be off|warn|error|preempt")
        if self.watchdog_warn_s <= 0 or self.watchdog_stall_s <= 0:
            raise ValueError("watchdog_warn_s/watchdog_stall_s must be > 0")
        if self.watchdog_stall_s < self.watchdog_warn_s:
            raise ValueError(
                "watchdog_stall_s must be >= watchdog_warn_s (warn first, "
                "then escalate)"
            )
        if self.watchdog_poll_s <= 0:
            raise ValueError("watchdog_poll_s must be > 0")
        if self.watchdog_heartbeat_every < 0:
            raise ValueError("watchdog_heartbeat_every must be >= 0")
        if self.sdc is not None and not isinstance(self.sdc, dict):
            # Lazy check (sdc.py imports jax at digest time): accept an
            # SDCConfig instance or a dict of its kwargs.
            if type(self.sdc).__name__ != "SDCConfig":
                raise ValueError(
                    "sdc must be an accelerate_tpu.sdc.SDCConfig or a dict "
                    f"of its kwargs, got {type(self.sdc).__name__}"
                )


@dataclass
class ElasticKwargs(KwargsHandler):
    """Elastic-resharding config (resharding.py). Passing this handler to
    ``Accelerator(kwargs_handlers=[...])`` turns the subsystem on; without it
    ``accelerator.elastic`` is ``None``, every hook site is a single ``None``
    check, and a topology-mismatched restore raises
    :class:`~accelerate_tpu.resharding.TopologyMismatchError` instead of
    resharding.

    - **Elastic restore** (``elastic_restore``): a checkpoint written on N
      devices restores on M≠N through a planned redistribution schedule —
      each leaf ingested under its *source* sharding spec (projected onto
      the new mesh) and redistributed on-device, batched so per-device bytes
      in flight never exceed ``staging_budget_mb``. Leaves that cannot fit
      even alone fall back to host-staged chunked ingest when
      ``host_stage_oversize`` is on.
    - **Live migration**: :meth:`Accelerator.migrate_plan` reshards the
      prepared ``TrainState`` (donated buffers; RNG, dataloader cursor and
      grad-accum state carried over) onto a new plan/layout mid-run and
      invalidates + optionally re-warms (``warm_after_migrate``) the
      compile-manager executables for the new shapes.
    - **Resize policy** (``resize_policy``): what an elastic relaunch
      (``ACCELERATE_RESTART_ATTEMPT`` > 0) does when it comes back on a
      different device count. ``"replan"`` re-runs the planner search under
      the new topology — pinning the model-parallel axes the calibration
      data says are winning when ``pin_winning_axes`` is on; ``"keep"``
      keeps the checkpoint's layout scaled to the new count; ``"fail"``
      refuses (same error as elastic off).
    """

    enabled: bool = True
    elastic_restore: bool = True
    staging_budget_mb: float = 256.0
    host_stage_oversize: bool = True
    resize_policy: str = "replan"  # replan | keep | fail
    pin_winning_axes: bool = True
    warm_after_migrate: bool = True

    def __post_init__(self):
        if self.resize_policy not in ("replan", "keep", "fail"):
            raise ValueError("resize_policy must be replan|keep|fail")
        if self.staging_budget_mb <= 0:
            raise ValueError("staging_budget_mb must be > 0")


@dataclass
class CompileKwargs(KwargsHandler):
    """Compile-manager config (compile_manager.py). Passing this handler to
    ``Accelerator(kwargs_handlers=[...])`` turns the subsystem on; without it
    ``accelerator.compile_manager`` is ``None`` and every hook site is a
    single ``None`` check (behavior byte-identical to the unmanaged path).

    - ``buckets``: shape-bucket policy applied at the device boundary.
      ``"pow2"`` rounds ragged dims up the power-of-two ladder, ``"fixed"``
      uses the explicit ``batch_buckets``/``seq_buckets`` ladders, ``"auto"``
      builds the ladder from the shapes manifest (previously observed shapes;
      falls back to pow2 for unseen sizes), ``None`` disables bucketing but
      keeps warmup + cache control.
    - ``bucket_batch`` / ``bucket_seq``: which dims get bucketed (axis 0 of
      every array leaf; axis 1 of rank>=2 leaves). The batch dim of a loader
      batch is padded to the loader's OWN batch size first, so the ragged
      final ``drop_last=False`` batch stops costing a one-off recompile each
      epoch.
    - ``min_bucket`` / ``max_bucket``: pow2-ladder floor and cap. A dim past
      ``max_bucket`` (or off a fixed ladder) falls through with a one-time
      warning and ships its true shape.
    - ``batch_pad_mode``: ``"repeat"`` cycles real samples (the semantics
      ``even_batches`` already gives the final batch; duplicates are trimmed
      by ``gather_for_metrics``) or ``"zero"``. Sequence padding always
      zero-fills with ``seq_pad_value``.
    - ``emit_mask``: on dict batches, ALWAYS add a ``mask_key`` leaf
      (1.0 = real element) so masked losses can ignore padding without the
      batch structure — and the compiled signature — ever changing.
    - ``warmup``: ``"execute"`` (default) runs the real jitted step on a
      copy of the train state per manifest signature (the only mode that
      populates jit's dispatch cache — zero recompiles after warmup);
      ``"aot"`` does ``lower(abstract).compile()`` (primes the persistent
      cache only); ``"off"`` disables. ``warmup_calls`` executions per
      signature absorb the donated-buffer layout specialization (default 2).
    - ``manifest_path``: shapes-manifest override; default
      ``<project_dir>/compile_cache/shapes_manifest.jsonl``.
    - ``cache_budget_bytes``: LRU prune budget for the persistent executable
      cache (falls back to ``JitConfig.persistent_cache_budget_bytes``).
    """

    enabled: bool = True
    buckets: Optional[str] = "pow2"  # pow2 | fixed | auto | None
    bucket_batch: bool = True
    bucket_seq: bool = True
    batch_buckets: Optional[list] = None
    seq_buckets: Optional[list] = None
    min_bucket: int = 8
    max_bucket: Optional[int] = None
    batch_pad_mode: str = "repeat"  # repeat | zero
    seq_pad_value: int = 0
    emit_mask: bool = False
    mask_key: str = "pad_mask"
    warmup: str = "execute"  # execute | aot | off
    warmup_calls: int = 2
    manifest_path: Optional[str] = None
    cache_budget_bytes: Optional[int] = None

    def __post_init__(self):
        if self.buckets not in (None, "pow2", "fixed", "auto"):
            raise ValueError("buckets must be one of pow2|fixed|auto|None")
        if self.batch_pad_mode not in ("repeat", "zero"):
            raise ValueError("batch_pad_mode must be repeat|zero")
        if self.warmup not in ("execute", "aot", "off"):
            raise ValueError("warmup must be execute|aot|off")


@dataclass
class AutoPlanKwargs(KwargsHandler):
    """Auto-parallelism planner config (planner.py). Passing this handler to
    ``Accelerator(kwargs_handlers=[...])`` — or passing
    ``Accelerator(parallelism_config="auto")`` — turns the subsystem on: the
    first ``prepare()`` call resolves a :class:`~accelerate_tpu.planner.ParallelPlan`
    for the prepared model (cached under ``<project_dir>/plans/``), installs
    its layout as the ``ParallelismConfig``, applies its remat policy, and —
    when a :class:`TelemetryKwargs` handler is also present — writes measured
    step time / peak HBM back into the plan artifact after
    ``calibrate_after`` steps so repeated runs tighten the cost model.
    Without the handler (and without ``"auto"``) nothing changes: no planner
    code runs and ``Accelerator`` behavior is byte-identical.

    - ``hbm_gib``: per-chip HBM budget the plan must fit (v5e: 16).
    - ``seq`` / ``per_chip_batch``: the training shape the plan is priced
      for. ``per_chip_batch`` is samples per chip at pure data parallelism —
      the global batch is ``per_chip_batch × device count`` for every
      candidate layout, so predicted step times compare.
    - ``axes``: mesh axes the search may raise above 1. Defaults to
      ``(dp_replicate, dp_shard, tp)`` — cp/pp/ep layouts need model/loss
      support the auto path cannot verify; enable them explicitly (the
      ``accelerate-tpu plan`` CLI searches all axes by default).
    - ``pinned``: axis → degree overrides the search must honor
      (``{"tp": 2}``); the rejection log shows what pinning cost.
    - ``bandwidths``: dict overriding :class:`~accelerate_tpu.planner.BandwidthTable`
      fields (ici_gbps, dcn_gbps, flops_per_chip, mfu, ...).
    - ``plans_dir``: artifact directory; default ``<project_dir>/plans``.
    - ``use_cache``: load a cached plan for identical inputs instead of
      re-searching (the cache key hashes every search input).
    - ``calibrate_after``: telemetry writes measured-vs-predicted step time
      and peak HBM into the plan after this many steps (0 disables).
    - ``apply_remat`` / ``apply_microbatches``: let the resolved plan flip
      ``config.remat`` on the prepared module / set gradient accumulation to
      the plan's microbatch count. Disable to treat the plan as advisory.
    """

    enabled: bool = True
    hbm_gib: float = 16.0
    seq: int = 2048
    per_chip_batch: int = 1
    optimizer: str = "adamw"
    axes: tuple = ("dp_replicate", "dp_shard", "tp")
    pinned: Optional[dict] = None
    bandwidths: Optional[dict] = None
    plans_dir: Optional[str] = None
    use_cache: bool = True
    calibrate_after: int = 10
    apply_remat: bool = True
    apply_microbatches: bool = True

    def __post_init__(self):
        if self.hbm_gib <= 0:
            raise ValueError(f"hbm_gib must be > 0, got {self.hbm_gib}")
        if self.seq < 1 or self.per_chip_batch < 1:
            raise ValueError("seq and per_chip_batch must be >= 1")
        from ..planner import ALL_SEARCH_AXES

        bad = set(self.axes) - set(ALL_SEARCH_AXES)
        if bad:
            raise ValueError(
                f"unknown search axes {sorted(bad)}; valid: {list(ALL_SEARCH_AXES)}"
            )


@dataclass
class ServingConfig(KwargsHandler):
    """Continuous-batching serving engine config (serving.py). OFF by
    default everywhere: nothing constructs a
    :class:`~accelerate_tpu.serving.ServingEngine` unless you do — the
    training path and plain ``generate()`` callers never touch serving
    code. Passing this handler to ``Accelerator(kwargs_handlers=[...])``
    only stores it (``accelerator.serving_config``) so
    ``accelerator.build_serving_engine(model)`` can construct an engine
    wired to the compile manager and telemetry recorder.

    - ``n_slots``: concurrent sequences — the slot-paged KV cache is
      ``(L, n_slots, max_len, Hkv, D)``; one decode tick advances every
      live slot. Size it to the HBM left after params: bigger = higher
      aggregate tokens/s, until the decode step goes compute-bound.
    - ``max_len``: per-slot capacity (prompt + continuation); default
      ``min(max_position_embeddings, 4096)``. ``submit`` rejects requests
      that cannot fit.
    - ``prefill_chunks``: explicit chunk-size ladder for chunked prefill;
      default: the compile manager's seq buckets when one is wired,
      else pow2 ``min_prefill_chunk..max_prefill_chunk``. Every possible
      prompt length compiles at most ``len(ladder)`` prefill executables.
    - ``prefill_chunks_per_tick``: prompt chunks interleaved per decode
      tick — raise to admit long prompts faster at some decode-latency
      cost (head-of-line control knob).
    - ``temperature`` / ``top_k`` / ``top_p`` / ``eos_token_id`` /
      ``pad_token_id``: sampling settings, engine-wide (the compiled decode
      step bakes them in). ``max_new_tokens`` is the default per-request
      budget; ``submit``/``run`` override it per request.
    - ``cache_dtype``: KV-cache dtype override (default: model dtype).
      ``jnp.int8`` switches the slot cache to quantized KV pages
      (``generation.QuantPages``: int8 data + per-page absmax scales) —
      attention dequantizes in-kernel and disagg handoff moves ~4x fewer
      bytes; see docs/usage_guides/serving.md "Quantized KV pages".
    - ``seed``: seeds the idle slots' PRNG pool; each request's stream is
      the ``rng`` passed at ``submit`` (default ``jax.random.key(0)``).
    - ``speculate_k``: speculative decoding — self-draft ``k`` tokens per
      slot per tick from an n-gram history match and verify all ``k+1``
      positions in ONE batched forward inside the same single jitted
      decode program (static ``(n_slots, k+1)`` shapes, so the
      zero-recompile invariant holds). ``0`` (default) keeps the plain
      one-token tick. Greedy output is bit-equal to non-speculative
      decode; sampled output draws through exact-distribution rejection
      sampling. See docs/usage_guides/serving.md "Speculative decoding".
    - ``speculate_ngram``: per-slot token-history window the self-draft
      matches against (the draft "model" capacity; >= 2).

    Admission control + SLOs (every request terminates with an explicit
    ``status`` in ``poll()`` results — ``ok | timeout | shed | failed``;
    see docs/usage_guides/serving.md "Serving under faults"):

    - ``max_queue_depth``: bound on the admission queue; ``None`` (default)
      keeps the unbounded pre-SLO behavior. When the bound is hit,
      ``overload_policy`` decides: ``"reject"`` sheds the NEW request
      immediately (status ``shed``), ``"shed_oldest"`` drops the oldest
      queued request to make room, ``"block"`` ticks the engine inside
      ``submit()`` until a queue slot frees (the hang guard still bounds a
      wedged engine).
    - ``deadline_s``: default per-request deadline, measured from
      ``submit()`` (override per request). Deadline checks run every tick;
      a timed-out request frees its slot immediately and finishes with
      status ``timeout``.
    - ``max_retries``: per-request recovery budget — how many times a
      request may be re-queued after a fault (poisoned slot, failed
      handoff, dead lane) before it finishes with status ``failed``.
      Resubmission is idempotent: the prompt + rng payload make the retry
      bit-equal to a fresh submit.
    - ``max_idle_ticks``: hang guard — after this many consecutive ticks
      with pending requests but no admission, prefill progress, live
      decode, or retirement, the engine raises
      :class:`~accelerate_tpu.serving.ServingStalledError` naming the stuck
      requests instead of spinning forever.
    - ``window_requests``: size of the rolling SLO window behind
      ``stats()["window"]`` (last N terminal requests + N per-tick
      queue-depth samples). Lifetime percentiles average the whole run, so
      a long healthy prefix masks a current breach; the autoscaler
      (autoscale.py) and canary gates read this window instead.

    Crash durability (journal.py — see docs/usage_guides/serving.md
    "Surviving engine crashes"):

    - ``journal_dir``: directory for the write-ahead request journal;
      ``None`` (default) keeps journaling fully off. With it set, every
      admission / progress batch / terminal status is durably logged and
      ``ServingEngine.recover()`` rebuilds the queue after a process death:
      completed requests return their cached rows (exactly-once — never
      re-executed), in-flight requests replay bit-equal from the journaled
      prompt + rng.
    - ``journal_fsync``: durability policy — ``"every_record"`` (fsync per
      append), ``"every_tick"`` (one fsync per engine tick; the default),
      or ``"os"`` (flush to the page cache only — survives a process crash,
      not host power loss).
    - ``journal_segment_records``: appends per WAL segment before rotation
      (seal + compaction of the sealed set).
    """

    enabled: bool = True
    n_slots: int = 8
    max_len: Optional[int] = None
    max_new_tokens: int = 32
    prefill_chunks: Optional[list] = None
    min_prefill_chunk: int = 16
    max_prefill_chunk: int = 256
    prefill_chunks_per_tick: int = 1
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    pad_token_id: Optional[int] = None
    cache_dtype: Any = None
    seed: int = 0
    speculate_k: int = 0
    speculate_ngram: int = 16
    max_queue_depth: Optional[int] = None
    overload_policy: str = "reject"
    deadline_s: Optional[float] = None
    max_retries: int = 2
    max_idle_ticks: int = 100
    window_requests: int = 128
    journal_dir: Optional[str] = None
    journal_fsync: str = "every_tick"
    journal_segment_records: int = 512

    def __post_init__(self):
        if self.n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.prefill_chunks_per_tick < 1:
            raise ValueError("prefill_chunks_per_tick must be >= 1")
        if self.min_prefill_chunk < 1 or self.max_prefill_chunk < self.min_prefill_chunk:
            raise ValueError(
                "need 1 <= min_prefill_chunk <= max_prefill_chunk, got "
                f"{self.min_prefill_chunk}..{self.max_prefill_chunk}"
            )
        if self.overload_policy not in ("reject", "shed_oldest", "block"):
            raise ValueError(
                "overload_policy must be 'reject', 'shed_oldest', or "
                f"'block', got {self.overload_policy!r}"
            )
        if self.max_queue_depth is not None and self.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1 (or None)")
        if self.deadline_s is not None and not self.deadline_s > 0:
            raise ValueError("deadline_s must be > 0 (or None)")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.max_idle_ticks < 1:
            raise ValueError("max_idle_ticks must be >= 1")
        if self.window_requests < 1:
            raise ValueError("window_requests must be >= 1")
        if self.journal_fsync not in ("every_record", "every_tick", "os"):
            raise ValueError(
                "journal_fsync must be 'every_record', 'every_tick', or "
                f"'os', got {self.journal_fsync!r}"
            )
        if self.journal_segment_records < 1:
            raise ValueError("journal_segment_records must be >= 1")
        if self.speculate_k < 0:
            raise ValueError("speculate_k must be >= 0")
        if self.speculate_ngram < 2:
            raise ValueError("speculate_ngram must be >= 2")


@dataclass
class DisaggConfig(KwargsHandler):
    """Disaggregated-serving config (disagg.py). OFF by default everywhere:
    nothing splits the device set unless you construct a
    :class:`~accelerate_tpu.disagg.DisaggServingEngine` — directly, or by
    passing this handler to ``Accelerator(kwargs_handlers=[...])`` so
    ``accelerator.build_serving_engine(model)`` upgrades the colocated
    engine to the two-mesh router. Training and the colocated serving path
    never touch this.

    - ``n_prefill_devices``: pin the prefill-slice size; default ``None``
      lets :func:`~accelerate_tpu.planner.plan_disagg_slices` size it from
      the prefill:decode FLOP ratio against the planner's BandwidthTable.
    - ``prefill_decode_flop_ratio``: measured prefill:decode FLOP ratio per
      request. Default ``None`` estimates it as
      ``expected_prompt_tokens / max_new_tokens`` (both phases cost ~2·P
      FLOPs/token on a dense causal LM).
    - ``expected_prompt_tokens``: expected mean prompt length for the ratio
      estimate; default: half the serving slot capacity.
    - ``n_prefill_lanes``: concurrent prefill workspaces on the prefill
      slice — each lane owns a ``(L, 1, T_max, Hkv, D)`` cache pinned to a
      prefill device (round-robin) and prefills one request at a time.
    - ``handoff_depth``: committed KV pages a lane may keep in flight to
      the decode mesh before the router drains the oldest — depth 2 is the
      double-buffer that overlaps a chunk's transfer with the next chunk's
      prefill.
    - ``handoff_retries`` / ``handoff_backoff_s`` / ``handoff_backoff_cap_s``:
      a failed KV-page transfer retries this many times with capped,
      deterministically-jittered exponential backoff before the engine
      quarantines the lane and re-queues its in-flight request (bounded by
      ``ServingConfig.max_retries``); see docs/usage_guides/serving.md
      "Serving under faults".
    - ``handoff_sample_every``: every Nth page transfer is timed end-to-end
      (a sampled ``block_until_ready``) to feed the telemetry ``disagg``
      block's handoff latency without stalling the pipeline on every page.
    - ``bandwidths``: BandwidthTable field overrides for the slice-sizing
      cost model (same dict shape as ``AutoPlanKwargs.bandwidths``).
    - ``shard_decode_slots``: shard the decode-side slot cache across the
      decode slice (requires ``n_slots % n_decode == 0``) instead of
      hosting it on the slice's first device. Off by default: jitted
      programs taking typed PRNG-key arrays under a multi-device
      NamedSharding occupy TWO dispatch-cache entries for ONE compiled
      executable (jax 0.4.37), so the sharded path reports
      ``decode_executables == 2`` even though exactly one program is ever
      compiled; the engine pre-warms both entries at init so the census
      stays flat (``steady_recompiles == 0``) either way.
    """

    enabled: bool = True
    n_prefill_devices: Optional[int] = None
    prefill_decode_flop_ratio: Optional[float] = None
    expected_prompt_tokens: Optional[float] = None
    n_prefill_lanes: int = 2
    handoff_depth: int = 2
    handoff_sample_every: int = 8
    handoff_retries: int = 2
    handoff_backoff_s: float = 0.001
    handoff_backoff_cap_s: float = 0.05
    bandwidths: Optional[dict] = None
    shard_decode_slots: bool = False

    def __post_init__(self):
        if self.n_prefill_devices is not None and self.n_prefill_devices < 1:
            raise ValueError("n_prefill_devices must be >= 1")
        if (self.prefill_decode_flop_ratio is not None
                and not self.prefill_decode_flop_ratio > 0):
            raise ValueError("prefill_decode_flop_ratio must be > 0")
        if (self.expected_prompt_tokens is not None
                and not self.expected_prompt_tokens > 0):
            raise ValueError("expected_prompt_tokens must be > 0")
        if self.n_prefill_lanes < 1:
            raise ValueError("n_prefill_lanes must be >= 1")
        if self.handoff_depth < 1:
            raise ValueError("handoff_depth must be >= 1")
        if self.handoff_sample_every < 1:
            raise ValueError("handoff_sample_every must be >= 1")
        if self.handoff_retries < 0:
            raise ValueError("handoff_retries must be >= 0")
        if self.handoff_backoff_s < 0 or self.handoff_backoff_cap_s < self.handoff_backoff_s:
            raise ValueError(
                "need 0 <= handoff_backoff_s <= handoff_backoff_cap_s, got "
                f"{self.handoff_backoff_s}..{self.handoff_backoff_cap_s}"
            )


@dataclass
class JitConfig(KwargsHandler):
    """Compilation policy — the role of the reference's TorchDynamoPlugin
    (reference: utils/dataclasses.py:1031-1118). XLA jit is always on; these
    knobs tune it. ``persistent_cache_dir`` is validated at Accelerator init
    (created; a one-time warning instead of silently handing a bad path to
    ``jax.config``) and managed — hit/size stats and LRU pruning — when a
    :class:`CompileKwargs` handler is present (compile_manager.py)."""

    donate_state: bool = True            # donate params/opt-state buffers to the step
    remat_policy: str = "none"           # none | full | dots_saveable | offload
    scan_layers: bool = True             # roll repeated blocks into lax.scan ("regional compile")
    persistent_cache_dir: Optional[str] = None
    # Only compiles slower than this hit the persistent cache (jax's own
    # knob; tiny executables cost more to deserialize than to rebuild).
    persistent_cache_min_compile_time_secs: float = 1.0
    # mtime-LRU prune budget applied at Accelerator.end_training (None = no
    # pruning; requires the compile manager).
    persistent_cache_budget_bytes: Optional[int] = None

    @classmethod
    def from_env(cls) -> "JitConfig":
        budget = os.environ.get("ACCELERATE_JIT_CACHE_BUDGET_BYTES")
        return cls(
            donate_state=parse_flag_from_env("ACCELERATE_JIT_DONATE", True),
            remat_policy=parse_choice_from_env("ACCELERATE_REMAT_POLICY", "none"),
            scan_layers=parse_flag_from_env("ACCELERATE_SCAN_LAYERS", True),
            persistent_cache_dir=os.environ.get("ACCELERATE_JIT_CACHE_DIR"),
            persistent_cache_min_compile_time_secs=float(
                os.environ.get("ACCELERATE_JIT_CACHE_MIN_COMPILE_S", "1.0") or 1.0
            ),
            persistent_cache_budget_bytes=int(budget) if budget else None,
        )


class ShardingStrategy(BaseEnum):
    """FSDP sharding strategy names kept from the reference
    (utils/dataclasses.py:1584-2190); each maps to a PartitionSpec policy."""

    FULL_SHARD = "FULL_SHARD"          # params+grads+opt state sharded (ZeRO-3)
    SHARD_GRAD_OP = "SHARD_GRAD_OP"    # grads+opt state sharded (ZeRO-2)
    NO_SHARD = "NO_SHARD"              # pure replication (DDP)
    HYBRID_SHARD = "HYBRID_SHARD"      # shard within dp_shard, replicate across dp_replicate


class StateDictType(BaseEnum):
    FULL_STATE_DICT = "FULL_STATE_DICT"
    SHARDED_STATE_DICT = "SHARDED_STATE_DICT"


@dataclass
class FullyShardedDataParallelPlugin(KwargsHandler):
    """ZeRO/FSDP policy → NamedSharding choices over the ``dp_shard`` axis.

    Keeps the reference's config surface (reference:
    utils/dataclasses.py:1584-2190, env decode :1900-1990) but the payload is
    just: which tensor classes shard over which mesh axes, the min size below
    which a param stays replicated, and state-dict format.
    """

    sharding_strategy: str = "FULL_SHARD"
    reshard_after_forward: bool = True      # FSDP2 naming (zero3 vs zero2 behavior)
    min_weight_size_to_shard: int = 2**11   # small params stay replicated (auto-wrap min_num_params analog)
    cpu_offload: bool = False               # optimizer state pinned to host memory
    # FULL_STATE_DICT: one gathered safetensors; SHARDED_STATE_DICT: 5GB-split
    # safetensors (still gathered to rank 0); DISTRIBUTED_STATE_DICT: orbax/
    # TensorStore — every process writes its own shards, no gather (pod scale).
    state_dict_type: str = "SHARDED_STATE_DICT"
    activation_checkpointing: bool = False
    mixed_precision_policy: Optional[MixedPrecisionPolicy] = None
    ignored_params: Optional[list] = None   # param-name regexes never sharded

    def __post_init__(self):
        env_prefix = "FSDP_"
        if isinstance(self.sharding_strategy, ShardingStrategy):
            self.sharding_strategy = str(self.sharding_strategy)
        self.sharding_strategy = os.environ.get(
            env_prefix + "SHARDING_STRATEGY", self.sharding_strategy
        ).upper()
        if self.sharding_strategy not in ShardingStrategy.list():
            # Accept the reference's FSDP2-style int codes 1-4.
            int_map = {"1": "FULL_SHARD", "2": "SHARD_GRAD_OP", "3": "NO_SHARD", "4": "HYBRID_SHARD"}
            self.sharding_strategy = int_map.get(self.sharding_strategy, self.sharding_strategy)
        if self.sharding_strategy not in ShardingStrategy.list():
            raise ValueError(
                f"sharding_strategy must be one of {ShardingStrategy.list()}"
            )
        self.cpu_offload = bool(
            str_to_bool(os.environ.get(env_prefix + "OFFLOAD_PARAMS", str(self.cpu_offload)))
        )
        self.state_dict_type = os.environ.get(
            env_prefix + "STATE_DICT_TYPE", self.state_dict_type
        ).upper()
        self.activation_checkpointing = bool(
            str_to_bool(
                os.environ.get(
                    env_prefix + "ACTIVATION_CHECKPOINTING", str(self.activation_checkpointing)
                )
            )
        )

    @property
    def shards_params(self) -> bool:
        return self.sharding_strategy in ("FULL_SHARD", "HYBRID_SHARD")

    @property
    def shards_grads_and_opt(self) -> bool:
        return self.sharding_strategy in ("FULL_SHARD", "HYBRID_SHARD", "SHARD_GRAD_OP")


@dataclass
class DeepSpeedPlugin(KwargsHandler):
    """ZeRO-stage compatibility shim (reference: utils/dataclasses.py:2550-3054).

    DeepSpeed does not exist on TPU; a ZeRO stage is exactly a sharding choice,
    so this plugin translates a DS config into a
    :class:`FullyShardedDataParallelPlugin`. Provided so users migrating DS
    configs keep working."""

    zero_stage: int = 2
    offload_optimizer_device: str = "none"
    offload_param_device: str = "none"
    gradient_accumulation_steps: int = 1
    gradient_clipping: Optional[float] = None
    zero3_init_flag: bool = False
    # Parsed from a ds_config's bf16/fp16 sections by from_ds_json — pass it
    # to Accelerator(mixed_precision=...) yourself; the plugin only carries it.
    mixed_precision: Optional[str] = None

    @classmethod
    def from_ds_json(
        cls, path: str, mixed_precision: "str | None" = None
    ) -> "DeepSpeedPlugin":
        """Build from a raw DeepSpeed ``ds_config.json`` — the file the
        reference's ``deepspeed_with_config_support`` example takes as
        ``--deepspeed_config_file`` (fixtures: reference
        tests/deepspeed/ds_config_zero{2,3}.json). ``"auto"`` values fall
        back to the field defaults; engine-only keys (optimizer, scheduler,
        comm backends) are ignored — the mesh owns those concerns.

        ``mixed_precision`` resolves ``bf16/fp16 {"enabled": "auto"}``
        sections, matching the reference's DeepSpeed integration where
        "auto" inherits the accelerate-level mixed-precision setting
        (reference: utils/deepspeed.py HfDeepSpeedConfig fill_match)."""
        import json

        with open(path) as f:
            cfg = json.load(f)

        def _noauto(v, default):
            return default if v in (None, "auto") else v

        # DeepSpeed semantics: NO zero_optimization section means ZeRO is
        # DISABLED (stage 0); "stage": "auto" means the engine default (2).
        z = cfg.get("zero_optimization")
        default_stage = 2 if z is not None else 0
        z = z or {}
        bf16_en = (cfg.get("bf16", {}) or {}).get("enabled")
        fp16_en = (cfg.get("fp16", {}) or {}).get("enabled")
        # "enabled": "auto" inherits the accelerate-level setting — only for
        # the matching section (an fp16 "auto" does not turn on bf16).
        if bf16_en == "auto":
            bf16_en = mixed_precision == "bf16"
        if fp16_en == "auto":
            fp16_en = mixed_precision == "fp16"
        mp = None
        if bf16_en is True:
            mp = "bf16"
        elif fp16_en is True:
            mp = "fp16"
        clip = _noauto(cfg.get("gradient_clipping"), None)
        return cls(
            zero_stage=int(_noauto(z.get("stage"), default_stage)),
            offload_optimizer_device=_noauto(
                (z.get("offload_optimizer") or {}).get("device"), "none"
            ),
            offload_param_device=_noauto(
                (z.get("offload_param") or {}).get("device"), "none"
            ),
            gradient_accumulation_steps=int(
                _noauto(cfg.get("gradient_accumulation_steps"), 1)
            ),
            gradient_clipping=None if clip is None else float(clip),
            mixed_precision=mp,
        )

    def to_fsdp_plugin(self) -> FullyShardedDataParallelPlugin:
        strategy = {0: "NO_SHARD", 1: "SHARD_GRAD_OP", 2: "SHARD_GRAD_OP", 3: "FULL_SHARD"}[
            self.zero_stage
        ]
        return FullyShardedDataParallelPlugin(
            sharding_strategy=strategy,
            cpu_offload=self.offload_optimizer_device == "cpu"
            or self.offload_param_device == "cpu",
        )


@dataclass
class TorchTensorParallelConfig(KwargsHandler):
    """TP config (reference: utils/dataclasses.py:2293-2313). The actual
    name→PartitionSpec rules live in parallel/tp.py."""

    tp_size: int = 1
    enable_async_tp: bool = False  # accepted, maps to XLA latency-hiding scheduler flags


@dataclass
class TorchContextParallelConfig(KwargsHandler):
    """CP config (reference: utils/dataclasses.py:2205-2231)."""

    cp_size: int = 1
    cp_comm_strategy: str = "alltoall"  # "allgather" gathers full KV; "alltoall" ring-rotates

    def __post_init__(self):
        if self.cp_comm_strategy not in ("allgather", "alltoall"):
            raise ValueError("cp_comm_strategy must be allgather|alltoall")


@dataclass
class SequenceParallelConfig(KwargsHandler):
    """Ulysses/ALST SP config (reference: DeepSpeedSequenceParallelConfig,
    utils/dataclasses.py:2233-2291)."""

    sp_size: int = 1
    attention_implementation: str = "native"  # native | flash (pallas)


@dataclass
class DataLoaderConfiguration(KwargsHandler):
    """(reference: utils/dataclasses.py:880-1030)"""

    split_batches: bool = False
    dispatch_batches: Optional[bool] = None
    even_batches: bool = True
    use_seedable_sampler: bool = True
    data_seed: Optional[int] = None
    non_blocking: bool = True
    use_stateful_dataloader: bool = False
    prefetch_size: int = 2
    # Dispatch mode: batches rank 0 ships per broadcast collective (the
    # fixed collective cost amortizer, byte-capped inside the loader).
    # 1 restores the one-collective-per-batch behavior.
    dispatch_group_size: int = 8


@dataclass
class ProjectConfiguration(KwargsHandler):
    """(reference: utils/dataclasses.py:780-878)"""

    project_dir: str = None
    logging_dir: str = None
    automatic_checkpoint_naming: bool = False
    total_limit: int = None
    iteration: int = 0
    save_on_each_node: bool = False
    # Elastic auto-resume (opt-in): on a gang restart
    # (ACCELERATE_RESTART_ATTEMPT > 0, commands/launch.py) the Accelerator
    # load_state()s the latest automatic checkpoint right after prepare(),
    # so a restarted run continues instead of silently training from scratch
    # (reference: torch elastic restarts, commands/launch.py:998-1030).
    automatic_resume: bool = False

    def set_directories(self, project_dir: str = None):
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self):
        self.set_directories(self.project_dir)


def add_model_config_to_megatron_parser(*args, **kwargs):  # pragma: no cover
    raise NotImplementedError(
        "Megatron-LM is a GPU engine; its TP/PP/SP/EP capabilities are native "
        "here via ParallelismConfig + parallel/ modules."
    )
