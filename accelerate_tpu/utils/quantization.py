"""int8 / int4 weight-only quantization (TPU-native re-design of the
reference's bitsandbytes integration: utils/bnb.py:44-473,
``BnbQuantizationConfig`` utils/dataclasses.py:3055).

bitsandbytes ships CUDA kernels; on TPU the same capability is expressed as
data layout + XLA ops:

- **int8**: per-output-channel symmetric scales (absmax/127). The MXU has a
  native int8 path, and the dequant (``q * s``) fuses into the consumer matmul.
- **int4**: linear 4-bit with *grouped* scales (``group_size`` input elements
  share one scale — the bnb blockwise idea) packed two nibbles per uint8, so
  storage is shape[..., K/2] bytes + fp16 scales.

Quantized leaves live in the params tree as :class:`QuantizedTensor` pytrees;
``load_and_quantize_model`` returns a ``Model`` whose forward dequantizes
inline under jit — XLA schedules the bf16 copies transiently (with scanned
layers, one block at a time), so HBM at rest holds only the packed weights.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@dataclasses.dataclass
class QuantizationConfig:
    """(reference: BnbQuantizationConfig, utils/dataclasses.py:3055-3180)"""

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    group_size: int = 64                      # int4 scale granularity (bnb blocksize)
    compute_dtype: Any = jnp.bfloat16         # dequantized matmul dtype
    skip_modules: Optional[list[str]] = None  # name regexes kept full precision
    keep_in_fp32_modules: Optional[list[str]] = None
    min_size_to_quantize: int = 2**12         # small tensors are not worth packing

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("load_in_8bit and load_in_4bit are mutually exclusive")
        if not (self.load_in_8bit or self.load_in_4bit):
            raise ValueError("Set load_in_8bit=True or load_in_4bit=True")
        if self.group_size % 2 != 0:
            raise ValueError("group_size must be even (two int4 per byte)")

    @property
    def bits(self) -> int:
        return 8 if self.load_in_8bit else 4


BnbQuantizationConfig = QuantizationConfig  # migration alias

# NF4 codebook (QLoRA): the 16 quantiles of N(0,1) normalized to [-1, 1] —
# information-theoretically optimal 4-bit levels for gaussian weights.
NF4_CODE = np.asarray(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)
# Decision boundaries = midpoints between adjacent levels (for searchsorted).
NF4_BOUNDARIES = (NF4_CODE[1:] + NF4_CODE[:-1]) / 2.0


@struct.dataclass
class QuantizedTensor:
    """A quantized weight leaf: packed data + scales + static metadata."""

    data: jax.Array                      # int8 (8-bit) or uint8 nibble-packed (4-bit)
    scales: jax.Array                    # fp32; per-channel (8b) or per-group (4b)
    shape: tuple = struct.field(pytree_node=False)
    bits: int = struct.field(pytree_node=False)
    group_size: int = struct.field(pytree_node=False, default=64)

    @property
    def nbytes_packed(self) -> int:
        return int(np.prod(self.data.shape)) * self.data.dtype.itemsize + self.scales.nbytes


def quantize_tensor_int8(w: jax.Array) -> QuantizedTensor:
    """Symmetric per-output-channel int8 (last dim = output features, the
    Dense kernel layout (in, out))."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=tuple(range(w32.ndim - 1)), keepdims=True)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scales), -127, 127).astype(jnp.int8)
    return QuantizedTensor(data=q, scales=scales, shape=tuple(w.shape), bits=8)


def quantize_tensor_int4(w: jax.Array, group_size: int = 64) -> QuantizedTensor:
    """NF4: per-group absmax normalization + nearest-NF4-level index, packed
    two 4-bit indices per uint8 byte. Groups run along the flattened leading
    (input, incl. stacked-layer) dims."""
    shape = tuple(w.shape)
    w2 = jnp.asarray(w, jnp.float32).reshape(-1, shape[-1])  # (lead_flat, out)
    k, n = w2.shape
    pad = (-k) % group_size
    if pad:
        w2 = jnp.concatenate([w2, jnp.zeros((pad, n), jnp.float32)], axis=0)
    g = w2.shape[0] // group_size
    grouped = w2.reshape(g, group_size, n)
    amax = jnp.max(jnp.abs(grouped), axis=1, keepdims=True)
    scales = jnp.where(amax > 0, amax, 1.0)                    # (g, 1, n)
    normalized = grouped / scales                              # in [-1, 1]
    idx = jnp.searchsorted(jnp.asarray(NF4_BOUNDARIES), normalized).astype(jnp.uint8)
    idx = idx.reshape(-1, n)                                   # (k+pad, n), even rows
    packed = (idx[1::2] << 4) | idx[0::2]                      # ((k+pad)/2, n)
    return QuantizedTensor(
        data=packed, scales=scales[:, 0, :], shape=shape, bits=4, group_size=group_size
    )


def _unpack_int4(packed: jax.Array) -> jax.Array:
    """uint8 bytes → NF4 indices in [0, 15], interleaved back to rows."""
    lo = (packed & 0xF).astype(jnp.uint8)
    hi = ((packed >> 4) & 0xF).astype(jnp.uint8)
    rows = jnp.stack([lo, hi], axis=1)                         # (k/2, 2, n)
    return rows.reshape(-1, packed.shape[-1])                  # (k, n)


def dequantize_tensor(qt, dtype=jnp.bfloat16) -> jax.Array:
    if isinstance(qt, DecodeQuant):
        return dequantize_decode_kernel(qt, dtype)
    if not isinstance(qt, QuantizedTensor):
        raise TypeError(f"not a quantized leaf: {type(qt).__name__}")
    if qt.bits == 8:
        return (qt.data.astype(jnp.float32) * qt.scales).astype(dtype).reshape(qt.shape)
    k = int(np.prod(qt.shape[:-1]))
    n = qt.shape[-1]
    idx = _unpack_int4(qt.data)                                # (k+pad, n)
    vals = jnp.asarray(NF4_CODE)[idx]                          # codebook lookup
    g = vals.shape[0] // qt.group_size
    grouped = vals.reshape(g, qt.group_size, n)
    w = grouped * qt.scales[:, None, :]
    return w.reshape(-1, n)[:k].reshape(qt.shape).astype(dtype)


@struct.dataclass
class DecodeQuant:
    """Int8 weight-only leaf for the KV-cache decode path.

    Same-shape int8 ``data`` + per-(layer, out-channel) fp32 ``scales``;
    BOTH fields keep the stacked leading layer dim, so ``lax.scan`` over the
    block tree slices them together (a :class:`QuantizedTensor`'s broadcast
    scales can't ride a scan). Dequantization happens at the matmul
    (``generation._kernel``), so XLA reads int8 from HBM and fuses the
    scale-multiply into the dot — roughly halving the weight traffic that
    dominates batch-1 decode.
    """

    data: jax.Array     # int8, original kernel shape
    scales: jax.Array   # fp32, (lead, 1, ..., 1, out)

    @property
    def nbytes_packed(self) -> int:
        return self.data.nbytes + self.scales.nbytes


def quantize_decode_kernel(w: jax.Array, input_axes: Optional[tuple] = None) -> DecodeQuant:
    """Symmetric int8 reducing over ``input_axes`` (the contraction dims of
    the matmul this kernel feeds), keeping a scale per every OUTPUT channel
    — including the heads dim of 4-D attention kernels, where a single
    outlier head must not coarsen the others' codes. Defaults to all middle
    dims (correct for (L, in, out) MLP layouts); callers with DenseGeneral
    layouts pass the true input dims (see ``quantize_model_for_decode``).
    The leading layer axis is never reduced so the leaf stays scannable."""
    w32 = jnp.asarray(w, jnp.float32)
    axes = input_axes if input_axes is not None else (tuple(range(1, w32.ndim - 1)) or (0,))
    amax = jnp.max(jnp.abs(w32), axis=axes, keepdims=True)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scales), -127, 127).astype(jnp.int8)
    return DecodeQuant(data=q, scales=scales)


def dequantize_decode_kernel(dq: DecodeQuant, dtype=jnp.bfloat16) -> jax.Array:
    return (dq.data.astype(jnp.float32) * dq.scales).astype(dtype)


def quantize_model_for_decode(model):
    """Return an inference-only copy of ``model`` whose stacked block
    kernels are int8 :class:`DecodeQuant` leaves. The Llama-family
    generation plan dequantizes them at each matmul; embeddings, the LM
    head, norms and biases stay full precision (the
    quantization-error-dominant tensors, same policy as
    ``load_and_quantize_model``). Llama-family layouts only — the other
    plans (GPT-2/NeoX/OPT/T5/Whisper) read kernels without the dequant
    hook, so quantizing them would crash mid-trace."""
    params = model.params
    try:
        block = params["model"]["layers"]["block"]
        block["self_attn"]["q_proj"]["kernel"]
    except (KeyError, TypeError):
        raise ValueError(
            "quantize_model_for_decode supports the Llama-family stacked "
            "(scan_layers=True) layout only; got a param tree without "
            "model/layers/block/self_attn — use load_and_quantize_model "
            "for generic weight-only quantized inference."
        ) from None

    def _q(tree, in_block=False, parent=""):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = _q(v, in_block or k == "block", parent=k)
            elif in_block and k == "kernel" and getattr(v, "ndim", 0) >= 2:
                # Input (contraction) dims by projection, matching the
                # generation plan's einsums: o_proj contracts (heads, D);
                # q/k/v and the MLP kernels contract the hidden dim only.
                input_axes = (1, 2) if parent == "o_proj" and v.ndim == 4 else (1,)
                out[k] = quantize_decode_kernel(v, input_axes=input_axes)
            else:
                out[k] = v
        return out

    class _DecodeQuantizedModel(type(model)):
        def __call__(self, *args, **kwargs):
            raise ValueError(
                "decode-quantized models only support generate()/"
                "speculative_generate(); run full forwards on the original "
                "Model (its weights are untouched)."
            )

    qm = _DecodeQuantizedModel.__new__(_DecodeQuantizedModel)
    qm.__dict__.update(model.__dict__)
    # Detach BEFORE assigning params: on a prepared model the params setter
    # writes through into the live accelerator train state (model.py), which
    # must keep its full-precision weights.
    qm._accelerator = None
    qm.params = _q(params)
    return qm


def is_quantized(leaf) -> bool:
    return isinstance(leaf, (QuantizedTensor, DecodeQuant))


def quantize_params(params, config: QuantizationConfig, sep: str = "/"):
    """Quantize eligible float leaves of a params pytree; returns the mixed
    tree (QuantizedTensor leaves + untouched small/skipped tensors).

    Eligibility mirrors bnb's module filter (utils/bnb.py:117-177): ≥2-D float
    tensors above ``min_size_to_quantize`` whose path matches no skip regex.
    1-D tensors (norms, biases) always stay full precision.
    """
    skip = [re.compile(p) for p in (config.skip_modules or [])]
    fp32_keep = [re.compile(p) for p in (config.keep_in_fp32_modules or [])]

    def _walk(prefix, node):
        if isinstance(node, dict):
            return {k: _walk(f"{prefix}{sep}{k}" if prefix else k, v) for k, v in node.items()}
        x = node
        if not (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)):
            return x
        if any(r.search(prefix) for r in fp32_keep):
            return jnp.asarray(x, jnp.float32)
        if (
            x.ndim < 2
            or int(np.prod(x.shape)) < config.min_size_to_quantize
            or any(r.search(prefix) for r in skip)
        ):
            return x
        if config.bits == 8:
            return quantize_tensor_int8(x)
        return quantize_tensor_int4(x, config.group_size)

    return _walk("", params)


def dequantize_params(params, dtype=jnp.bfloat16):
    """Inline dequantization of a mixed tree (call inside jit: XLA fuses the
    ``q * s`` into consumers and frees the bf16 copies after use)."""
    return jax.tree.map(
        lambda x: dequantize_tensor(x, dtype) if is_quantized(x) else x,
        params,
        is_leaf=is_quantized,
    )


def quantized_nbytes(params) -> int:
    """HBM-at-rest footprint of a mixed tree."""
    total = 0
    for leaf in jax.tree.leaves(params, is_leaf=is_quantized):
        if is_quantized(leaf):
            total += leaf.nbytes_packed
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


def load_and_quantize_model(
    model,
    quantization_config: QuantizationConfig,
):
    """Quantize a loaded :class:`~accelerate_tpu.model.Model` in place for
    inference (reference: utils/bnb.py:44-116 ``load_and_quantize_model``).

    The returned model's forward dequantizes under jit to
    ``config.compute_dtype``. When ``skip_modules`` is unset, embeddings and
    the LM head stay full precision — bnb converts only ``nn.Linear`` modules
    (reference: utils/bnb.py:117-177, default ``modules_to_not_convert``
    includes the output head), and those two dominate quantization error.
    """
    from ..model import Model

    if quantization_config.skip_modules is None:
        quantization_config = dataclasses.replace(
            quantization_config, skip_modules=["lm_head", "embed"]
        )
    q_tree = quantize_params(model.params, quantization_config)
    module = model.module
    if module is None:
        raise ValueError(
            "load_and_quantize_model needs a Model built from a flax module "
            "(Model.from_flax); apply_fn-only models have no module to re-apply."
        )
    dtype = quantization_config.compute_dtype

    @jax.jit
    def _fwd(qp, extra_state, args, rngs, kwargs):
        call = {"rngs": rngs} if rngs else {}
        variables = {"params": dequantize_params(qp, dtype)}
        if extra_state:
            variables.update(extra_state)  # batch_stats / cache collections
        return module.apply(variables, *args, **call, **kwargs)

    class _QuantizedModel(Model):
        def __call__(self, *args, rngs=None, train: bool = False, **kwargs):
            if train:
                raise ValueError(
                    "Weight-only quantized models are inference-only "
                    "(the reference's bnb models are too, utils/bnb.py:44-116)."
                )
            return _fwd(self.params, self.extra_state, args, rngs, kwargs)

    qm = _QuantizedModel.__new__(_QuantizedModel)
    qm.__dict__.update(model.__dict__)
    qm._accelerator = None  # detached inference model: never write back into a train state
    qm.params = q_tree
    qm.quantization_config = quantization_config
    return qm
