"""Cross-process operations on tensors and nested structures (layer L1).

Re-design of the reference's ``utils/operations.py`` (reference:
src/accelerate/utils/operations.py:85-991). Two fundamentally different
regimes exist under JAX, and this module unifies them behind the reference's
API:

1. **Inside jit** (the data plane): collectives are XLA ops — a sharded
   ``jax.Array`` is already "gathered" logically; GSPMD inserts the actual
   all-gathers/psums. Nothing here runs per-training-step.

2. **Host side / out-of-band** (the control plane): per-process numpy data
   (e.g. metric batches, python objects) crossing process boundaries uses
   ``jax.experimental.multihost_utils`` — a tiny jitted all-gather under the
   hood. This is the moral equivalent of the reference's gloo side-channel.

Single-process (1 host, N local devices) needs no inter-process traffic at
all: "gather" is just fetching the global array.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np


def _partial_state():
    # Imported lazily: utils/__init__ loads before state.py finishes.
    from ..state import PartialState

    return PartialState()


class DistributedOperationException(Exception):
    """Raised when a cross-process op is called with mismatching shapes across
    ranks (reference: utils/operations.py:361-380)."""


class _CollectiveCounters:
    """Process-wide count + payload-bytes tally of the control-plane
    collectives in this module, consumed by the telemetry subsystem
    (telemetry.py). Disabled (a single bool check per call) unless a
    TelemetryRecorder is live."""

    __slots__ = ("enabled", "counts", "bytes")

    def __init__(self):
        self.enabled = False
        self.counts: dict = {}
        self.bytes: dict = {}

    def record(self, op: str, tensor) -> None:
        if not self.enabled:
            return
        nbytes = 0
        try:
            for leaf in jax.tree_util.tree_leaves(tensor):
                nbytes += int(getattr(leaf, "nbytes", 0) or 0)
        except Exception:
            pass
        self.counts[op] = self.counts.get(op, 0) + 1
        self.bytes[op] = self.bytes.get(op, 0) + nbytes

    def snapshot(self) -> dict:
        return {
            op: {"count": n, "bytes": self.bytes.get(op, 0)}
            for op, n in sorted(self.counts.items())
        }

    def reset(self) -> None:
        self.counts.clear()
        self.bytes.clear()


collective_counters = _CollectiveCounters()


# ---------------------------------------------------------------------------
# Nested-structure plumbing (pytrees make most of the reference's manual
# recursion free, but we keep the honest-recursion versions so Mapping
# subclasses and namedtuples survive round-trips like the reference's,
# utils/operations.py:85-180).
# ---------------------------------------------------------------------------

def is_tensor_information(obj) -> bool:
    return isinstance(obj, TensorInformation)


def is_namedtuple(data) -> bool:
    return isinstance(data, tuple) and hasattr(data, "_asdict") and hasattr(data, "_fields")


def honor_type(obj, generator):
    """Rebuild a sequence preserving its exact type (incl. namedtuples)."""
    if is_namedtuple(obj):
        return type(obj)(*list(generator))
    return type(obj)(generator)


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable = None,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to every leaf matching ``test_type`` in a nested
    list/tuple/dict structure (reference: utils/operations.py:85-130)."""
    if test_type is None:
        test_type = is_array_like
    if isinstance(data, (tuple, list)):
        return honor_type(
            data,
            (
                recursively_apply(
                    func, o, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for o in data
            ),
        )
    if isinstance(data, Mapping):
        return type(data)(
            {
                k: recursively_apply(
                    func, v, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for k, v in data.items()
            }
        )
    if test_type(data):
        return func(data, *args, **kwargs)
    if error_on_other_type:
        raise TypeError(
            f"Unsupported type {type(data)} passed — only nested "
            f"list/tuple/dict of objects satisfying {test_type.__name__} are supported."
        )
    return data


def is_array_like(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) or hasattr(x, "__jax_array__")


def send_to_device(tensor, device=None, non_blocking: bool = True, skip_keys=None):
    """Move a nested structure onto device(s). ``device`` may be a Device, a
    ``Sharding``, or None (default device). jax.device_put is async by nature
    so ``non_blocking`` is honored for free
    (reference: utils/operations.py:132-180)."""

    def _send(t):
        return jax.device_put(t, device)

    if skip_keys is None:
        skip_keys = []
    if isinstance(tensor, Mapping) and skip_keys:
        return type(tensor)(
            {
                k: (v if k in skip_keys else send_to_device(v, device, non_blocking))
                for k, v in tensor.items()
            }
        )
    return recursively_apply(_send, tensor)


def get_data_structure(data):
    """Nested structure of :class:`TensorInformation` describing ``data``
    (for broadcast-by-shape, reference: utils/operations.py:238-258)."""

    def _get_info(tensor):
        return TensorInformation(shape=tuple(tensor.shape), dtype=np.dtype(tensor.dtype))

    return recursively_apply(_get_info, data)


def get_shape(data):
    def _get_shape(tensor):
        return list(tensor.shape)

    return recursively_apply(_get_shape, data)


def initialize_tensors(data_structure):
    """Materialize empty tensors from a :func:`get_data_structure` skeleton."""

    def _init(info):
        return jnp.zeros(info.shape, dtype=info.dtype)

    return recursively_apply(_init, data_structure, test_type=is_tensor_information)


class TensorInformation:
    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype

    def __repr__(self):
        return f"TensorInformation(shape={self.shape}, dtype={self.dtype})"

    def __eq__(self, other):
        return (
            isinstance(other, TensorInformation)
            and tuple(self.shape) == tuple(other.shape)
            and self.dtype == other.dtype
        )


def find_batch_size(data) -> int:
    """First dim of the first tensor found (reference: utils/operations.py:220-236)."""
    if isinstance(data, (tuple, list)):
        for d in data:
            try:
                return find_batch_size(d)
            except (TypeError, ValueError):
                continue
        raise ValueError("Cannot find the batch size from empty sequence.")
    if isinstance(data, Mapping):
        for v in data.values():
            try:
                return find_batch_size(v)
            except (TypeError, ValueError):
                continue
        raise ValueError("Cannot find the batch size from empty dict.")
    if not is_array_like(data):
        raise TypeError(f"Can only find the batch size of arrays but got {type(data)}.")
    if len(data.shape) == 0:
        raise ValueError("Cannot find the batch size of a 0-dim array.")
    return data.shape[0]


def iterate_over_batch(data, start: int, end: int):
    """Slice every leaf's batch dim — the reference's ``slice_tensors``
    (reference: utils/operations.py:699-720)."""

    def _slice(tensor):
        return tensor[start:end]

    return recursively_apply(_slice, data)


slice_tensors = iterate_over_batch


def concatenate(data, dim: int = 0):
    """Concatenate a list of nested structures leaf-wise
    (reference: utils/operations.py:722-744)."""
    if isinstance(data[0], (tuple, list)):
        return honor_type(data[0], (concatenate([d[i] for d in data], dim=dim) for i in range(len(data[0]))))
    if isinstance(data[0], Mapping):
        return type(data[0])({k: concatenate([d[k] for d in data], dim=dim) for k in data[0].keys()})
    if not is_array_like(data[0]):
        raise TypeError(f"Can only concatenate arrays but got {type(data[0])}")
    return jnp.concatenate([jnp.asarray(d) for d in data], axis=dim)


# ---------------------------------------------------------------------------
# Cross-process collectives (control plane).
# ---------------------------------------------------------------------------

def _world():
    state = _partial_state()
    return state.num_processes


def _process_allgather(x, tiled: bool):
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=tiled)


def verify_operation(function):
    """Debug-mode decorator: before running a collective, gather every rank's
    leaf shapes and raise :class:`DistributedOperationException` naming the
    mismatching ranks (reference: utils/operations.py:361-422)."""
    import functools

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        state = _partial_state()
        if not getattr(state, "debug", False) or state.num_processes <= 1:
            return function(*args, **kwargs)
        tensor = kwargs.get("tensor", args[0] if args else None)
        shapes = get_shape(tensor)
        output = gather_object([shapes])
        if output[0] is not None and not all(o == output[0] for o in output):
            bad = [i for i, o in enumerate(output) if o != output[0]]
            raise DistributedOperationException(
                f"Cannot apply the desired operation due to shape mismatches. "
                f"All shapes across devices must be valid.\n\nOperation: `{function.__name__}`\n"
                f"Input shapes:\n" + "\n".join(f"  - Process {i}: {o}" for i, o in enumerate(output))
                + f"\nMismatched processes: {bad}"
            )
        return function(*args, **kwargs)

    return wrapper


@verify_operation
def gather(tensor):
    """Gather values from all processes, concatenated on dim 0.

    - A globally-sharded ``jax.Array`` is already logically global: return it
      fully replicated on host (``jax.device_get`` handles cross-process
      fetch via the runtime).
    - Per-process local numpy/host data: tiled all-gather across processes
      (reference semantics of ``_gpu_gather``, utils/operations.py:307-358).
    """
    collective_counters.record("gather", tensor)
    if _world() == 1:
        def _maybe_devget(t):
            return np.asarray(t)

        return recursively_apply(_maybe_devget, tensor)

    def _gather_one(t):
        t = np.asarray(t) if not isinstance(t, jax.Array) else t
        if isinstance(t, jax.Array) and not t.is_fully_addressable:
            # Already a global array — fetch replicated value.
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(t, tiled=True))
        return np.asarray(_process_allgather(np.asarray(t), tiled=True))

    return recursively_apply(_gather_one, tensor)


def gather_object(object: Any):
    """Gather arbitrary picklable python objects from all processes into a
    list ordered by rank (reference: utils/operations.py:424-452). Implemented
    as pickle → padded uint8 tensor → all-gather — the out-of-band channel the
    reference gets from gloo."""
    state = _partial_state()
    if state.num_processes == 1:
        return [object] if not isinstance(object, list) else object
    payload = pickle.dumps(object)
    local_len = np.array([len(payload)], dtype=np.int64)
    all_lens = _process_allgather(local_len, tiled=True)
    max_len = int(all_lens.max())
    buf = np.zeros((max_len,), dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = _process_allgather(buf, tiled=False)  # (world, max_len)
    out = []
    for i in range(state.num_processes):
        n = int(all_lens[i])
        obj = pickle.loads(gathered[i, :n].tobytes())
        if isinstance(object, list):
            out.extend(obj)
        else:
            out.append(obj)
    return out


@verify_operation
def broadcast(tensor, from_process: int = 0):
    """Broadcast a (nested) tensor from one process to all
    (reference: utils/operations.py:474-494)."""
    collective_counters.record("broadcast", tensor)
    if _world() == 1:
        return tensor
    from jax.experimental import multihost_utils

    def _bcast(t):
        arr = np.asarray(t)
        out = np.asarray(
            multihost_utils.broadcast_one_to_all(
                arr, is_source=_partial_state().process_index == from_process
            )
        )
        return out.reshape(arr.shape)  # 0-d leaves must stay 0-d

    return recursively_apply(_bcast, tensor)


# One collective costs the same for any payload up to ~1 MB (fixed dispatch
# cost dominates; benchmarks/input_pipeline_bench.py), so small objects ride
# a single fixed-size broadcast with the length inline — halving the fixed
# cost vs the naive length-round-then-data protocol. Larger payloads fall
# back to a second, exact-size collective; the header makes the decision
# from broadcast content, so every rank takes the same branch.
_BCAST_INLINE_BUCKET = 1 << 16


def broadcast_object_list(object_list: list, from_process: int = 0):
    """Broadcast a list of picklable objects from one process
    (reference: utils/operations.py:496-516)."""
    state = _partial_state()
    if state.num_processes == 1:
        return object_list
    from jax.experimental import multihost_utils

    is_src = state.process_index == from_process
    payload = pickle.dumps(list(object_list)) if is_src else b""
    buf = np.zeros((8 + _BCAST_INLINE_BUCKET,), dtype=np.uint8)
    if is_src:
        buf[:8] = np.frombuffer(
            np.int64(len(payload)).tobytes(), dtype=np.uint8
        )
        if len(payload) <= _BCAST_INLINE_BUCKET:
            buf[8: 8 + len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf, is_source=is_src))
    need = int(np.frombuffer(out[:8].tobytes(), dtype=np.int64)[0])
    if need <= _BCAST_INLINE_BUCKET:
        result = pickle.loads(out[8: 8 + need].tobytes())
    else:
        big = np.zeros((need,), dtype=np.uint8)
        if is_src:
            big[:] = np.frombuffer(payload, dtype=np.uint8)
        out2 = multihost_utils.broadcast_one_to_all(big, is_source=is_src)
        result = pickle.loads(np.asarray(out2).tobytes())
    for i, v in enumerate(result):
        object_list[i] = v
    return object_list


def is_global_array(t) -> bool:
    """True for a jax.Array that is already logically global over the mesh —
    reducing/gathering it per-process would double count."""
    return isinstance(t, jax.Array) and (
        not t.is_fully_addressable or getattr(t.sharding, "num_devices", 1) > 1
    )


def to_global_host(tree):
    """Fetch a pytree to host numpy, multi-host safe: leaves spanning
    non-addressable devices go through process_allgather (every process gets
    the assembled global value); fully-addressable leaves are a plain fetch.
    Used by checkpointing/save_model (reference analog: ZeRO3 16-bit gather in
    get_state_dict, accelerator.py:4002-4072)."""

    def _fetch(t):
        # np.asarray of a TPU array can expose the device's tiled layout as a
        # strided view; downstream writers (safetensors, memmap, ctypes)
        # assume C order, so normalize here at the host boundary. Reshape
        # AFTER ascontiguousarray: it promotes 0-d arrays to 1-d, which is how
        # round 1's LocalSGD corrupted scalar params to shape (1,).
        if isinstance(t, jax.Array) and not t.is_fully_addressable:
            from jax.experimental import multihost_utils

            out = np.asarray(multihost_utils.process_allgather(t, tiled=True))
            return np.ascontiguousarray(out).reshape(t.shape)
        arr = np.asarray(t)
        return np.ascontiguousarray(arr).reshape(arr.shape)

    return recursively_apply(_fetch, tree)


@verify_operation
def reduce(tensor, reduction: str = "mean", scale: float = 1.0):
    """Reduce a (nested) tensor across processes (sum or mean), applying
    ``scale`` (reference: utils/operations.py:746-788).

    Per-process host values are summed across ranks; an already-global
    jax.Array (a jit output) is by definition identical on every rank, so the
    cross-process reduce is an identity on it — only ``scale`` applies."""
    collective_counters.record("reduce", tensor)

    def _reduce_one(t):
        if is_global_array(t) and _world() > 1:
            return jnp.asarray(to_global_host(t) * scale)
        arr = np.asarray(t)
        if _world() > 1:
            stacked = _process_allgather(arr, tiled=False)
            # stack axis 0 is the process axis; summing it must restore the
            # input shape exactly (0-d leaves included — process_allgather
            # promotes scalars, see test_utils/scripts/test_ops.py).
            arr = np.sum(np.asarray(stacked).reshape((_world(),) + arr.shape), axis=0)
            if reduction == "mean":
                arr = arr / _world()
        return jnp.asarray(arr * scale)

    return recursively_apply(_reduce_one, tensor)


@verify_operation
def pad_across_processes(tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad every process's tensor along ``dim`` to the max size across
    processes so a subsequent ``gather`` is legal
    (reference: utils/operations.py:790-840)."""
    collective_counters.record("pad_across_processes", tensor)

    def _pad_one(t):
        if is_global_array(t) and _world() > 1:
            return t  # global arrays already have one consistent shape
        t = jnp.asarray(t)
        if dim >= t.ndim:
            return t
        size = np.array([t.shape[dim]], dtype=np.int64)
        if _world() > 1:
            sizes = np.asarray(_process_allgather(size, tiled=True))
            max_size = int(sizes.max())
        else:
            max_size = int(size[0])
        if max_size == t.shape[dim]:
            return t
        pad_amount = max_size - t.shape[dim]
        pad_width = [(0, 0)] * t.ndim
        pad_width[dim] = (pad_amount, 0) if pad_first else (0, pad_amount)
        return jnp.pad(t, pad_width, constant_values=pad_index)

    return recursively_apply(_pad_one, tensor)


def pad_input_tensors(tensor, batch_size: int, num_processes: int, dim: int = 0):
    """Pad a batch so it divides evenly by ``num_processes``, repeating the
    first samples (reference: utils/operations.py:842-888)."""

    def _pad_one(t):
        t = jnp.asarray(t)
        if batch_size % num_processes == 0:
            return t
        target = int(np.ceil(batch_size / num_processes)) * num_processes
        extra = target - t.shape[dim]
        idx = jnp.arange(extra) % t.shape[dim]
        return jnp.concatenate([t, jnp.take(t, idx, axis=dim)], axis=dim)

    return recursively_apply(_pad_one, tensor)


def copy_tensor_to_devices(tensor):
    """Replicate a host tensor onto all local devices."""
    sharding = jax.sharding.NamedSharding(
        jax.sharding.Mesh(np.asarray(jax.devices()).reshape(-1), ("x",)),
        jax.sharding.PartitionSpec(),
    )
    return recursively_apply(lambda t: jax.device_put(jnp.asarray(t), sharding), tensor)


def convert_to_fp32(tensor):
    """Upcast floating leaves to fp32 (the reference wraps autocast forwards
    with this, utils/operations.py:889-949)."""

    def _convert(t):
        if jnp.issubdtype(jnp.asarray(t).dtype, jnp.floating):
            return jnp.asarray(t, dtype=jnp.float32)
        return t

    return recursively_apply(_convert, tensor)


def convert_outputs_to_fp32(model_forward):
    import functools

    @functools.wraps(model_forward)
    def forward(*args, **kwargs):
        return convert_to_fp32(model_forward(*args, **kwargs))

    return forward


def listify(data):
    """Convert arrays to plain python lists for logging
    (reference: tracking.py helper)."""

    def _listify(t):
        return np.asarray(t).tolist()

    return recursively_apply(_listify, data)


def save(obj, f, save_on_each_node: bool = False, safe_serialization: bool = True):
    """Persist ``obj`` to disk, only on main process unless
    ``save_on_each_node`` (reference: utils/other.py:384-433)."""
    from ..state import PartialState

    state = _partial_state()
    if state.is_main_process or save_on_each_node:
        if safe_serialization and _is_flat_array_dict(obj):
            from .other import save_safetensors

            save_safetensors(obj, f)
        else:
            with open(f, "wb") as fh:
                pickle.dump(obj, fh)


def _is_flat_array_dict(obj) -> bool:
    return isinstance(obj, dict) and all(is_array_like(v) for v in obj.values())
