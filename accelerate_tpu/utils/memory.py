"""Device-memory helpers and the OOM-retry decorator.

Reference: src/accelerate/utils/memory.py:40-187.
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Callable

import jax


def release_memory(*objects):
    """Drop references and force a GC + device buffer sweep
    (reference: utils/memory.py:40-63)."""
    if not isinstance(objects, list):
        objects = list(objects)
    for i in range(len(objects)):
        if hasattr(objects[i], "delete"):
            try:
                objects[i].delete()
            except Exception:
                pass
        objects[i] = None
    clear_device_cache(garbage_collection=True)
    return objects


def clear_device_cache(garbage_collection: bool = False):
    """GC + ask the backend to free cached buffers
    (reference: utils/memory.py:65-80). XLA's allocator reclaims buffers when
    their jax.Arrays die, so GC is the main lever."""
    if garbage_collection:
        gc.collect()
    try:
        for buf in jax.live_arrays():
            # live_arrays() is advisory; arrays still referenced are untouched.
            pass
    except Exception:
        pass


def get_device_memory_stats(device=None) -> dict:
    """Per-device HBM stats (bytes_in_use / bytes_limit where the backend
    reports them)."""
    device = device or jax.devices()[0]
    try:
        return dict(device.memory_stats() or {})
    except Exception:
        return {}


def live_bytes_on_device(device=None):
    """Bytes of live jax.Arrays resident on ``device`` — the fallback gauge
    for backends whose ``memory_stats()`` is None (the virtual CPU mesh).
    Counts committed array shards only (not executable workspace), so it
    tracks the persistent tensor state the memory planner prices. Returns
    None when the live-array census is unavailable."""
    device = device or jax.devices()[0]
    try:
        arrays = jax.live_arrays()
    except Exception:
        return None
    total = 0
    for arr in arrays:
        try:
            for shard in arr.addressable_shards:
                if shard.device == device and shard.data is not None:
                    total += shard.data.nbytes
        except Exception:
            continue
    return total


def should_reduce_batch_size(exception: Exception) -> bool:
    """Heuristically detect an XLA out-of-memory failure
    (reference: utils/memory.py:82-100 checks CUDA OOM strings)."""
    msgs = (
        "RESOURCE_EXHAUSTED",
        "Out of memory",
        "out of memory",
        "Resource exhausted",
        "Attempting to allocate",
    )
    text = str(exception)
    return isinstance(exception, (RuntimeError, jax.errors.JaxRuntimeError)) and any(
        m in text for m in msgs
    )


def find_executable_batch_size(
    function: Callable = None, starting_batch_size: int = 128, reduce_batch_size_fn: Callable = None
):
    """Decorator retrying ``function(batch_size, ...)`` with a smaller batch on
    OOM — halves each retry like the reference's 0.9/0.5 policy
    (reference: utils/memory.py:119-187)."""
    if function is None:
        return functools.partial(
            find_executable_batch_size,
            starting_batch_size=starting_batch_size,
            reduce_batch_size_fn=reduce_batch_size_fn,
        )
    if reduce_batch_size_fn is None:
        reduce_batch_size_fn = lambda bs: bs // 2

    batch_size_holder = [starting_batch_size]

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        nonlocal batch_size_holder
        batch_size_holder[0] = starting_batch_size
        clear_device_cache(garbage_collection=True)
        params = list(inspect.signature(function).parameters.keys())
        if len(params) < (len(args) + 1):
            arg_str = ", ".join([f"{arg}={value}" for arg, value in zip(params[1:], args[1:])])
            raise TypeError(
                f"Batch size was passed into `{function.__name__}` as the first argument when called."
                f"Remove this as the decorator already does so: `{function.__name__}({arg_str})`"
            )
        while True:
            if batch_size_holder[0] == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                return function(batch_size_holder[0], *args, **kwargs)
            except Exception as e:
                if should_reduce_batch_size(e):
                    clear_device_cache(garbage_collection=True)
                    batch_size_holder[0] = reduce_batch_size_fn(batch_size_holder[0])
                else:
                    raise

    return wrapper
