"""Checkpoint consolidation utilities.

Reference analog: utils/fsdp_utils.py:338-420 ``merge_fsdp_weights`` (torch
DCP shard dirs -> one safetensors). Both of this framework's checkpoint
formats consolidate here:

- the gathered format is already name-keyed sharded safetensors — merging is
  a shard-join;
- the DISTRIBUTED_STATE_DICT format (orbax/TensorStore ``distributed_state``
  dirs) restores params host-side (no mesh needed) and writes safetensors.

The ``accelerate-tpu merge-weights`` CLI wraps the same function.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .constants import MODEL_NAME, ORBAX_DIR_NAME
from .other import flatten_state_dict, load_sharded_safetensors, save_safetensors, save_sharded_safetensors

__all__ = ["merge_fsdp_weights"]


def _load_distributed_params(ckpt_dir: str) -> dict:
    """Host-side restore of ONLY the params subtree of an orbax checkpoint —
    no mesh, no shardings. Partial restore matters: the checkpoint also holds
    optimizer state (Adam: 2-3x the param bytes) that a merge must not
    materialize. Falls back to a full restore if this orbax version lacks
    partial_restore."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(ckpt_dir, ORBAX_DIR_NAME))
    try:
        with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
            meta = ckptr.metadata(path)
            abstract = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), meta.tree["params"]
            )
            payload = ckptr.restore(
                path, args=ocp.args.PyTreeRestore(item={"params": abstract}, partial_restore=True)
            )
        params = payload["params"]
    except Exception:
        with ocp.StandardCheckpointer() as ckptr:
            payload = ckptr.restore(path)
        params = payload.get("params", payload)
    return {k: np.asarray(v) for k, v in flatten_state_dict(params).items()}


def merge_fsdp_weights(
    checkpoint_dir: str,
    output_dir: str,
    *,
    weights_name: Optional[str] = None,
    output_name: Optional[str] = None,
    max_shard_size: Optional[str] = None,
) -> str:
    """Consolidate a ``save_state`` checkpoint into portable safetensors.

    Handles both formats: a ``distributed_state`` (orbax) dir restores
    host-side; sharded safetensors join. ``max_shard_size`` re-shards the
    output (e.g. ``"5GB"``) instead of writing one file. Returns the output
    path (file, or directory when re-sharded).
    """
    weights_name = weights_name or f"{MODEL_NAME}.safetensors"
    if os.path.isdir(os.path.join(checkpoint_dir, ORBAX_DIR_NAME)):
        flat = _load_distributed_params(checkpoint_dir)
    else:
        flat = load_sharded_safetensors(checkpoint_dir, weights_name=weights_name)
    if not flat:
        raise FileNotFoundError(
            f"No {weights_name} shards or {ORBAX_DIR_NAME} dir found in {checkpoint_dir}"
        )
    os.makedirs(output_dir, exist_ok=True)
    out_name = output_name or weights_name
    if max_shard_size:
        save_sharded_safetensors(
            flat, output_dir, weights_name=out_name, max_shard_size=max_shard_size
        )
        return output_dir
    out_path = os.path.join(output_dir, out_name)
    save_safetensors(flat, out_path)
    return out_path
