"""Environment-variable helpers.

Config flows through environment variables, same architectural decision as the
reference (reference: src/accelerate/utils/environment.py and SURVEY.md §1):
the launcher encodes choices as ``ACCELERATE_*`` / ``PARALLELISM_CONFIG_*``
vars, worker processes decode them.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Iterator


def honor_jax_platforms_env() -> None:
    """Re-assert an explicit ``JAX_PLATFORMS`` request through jax.config.

    The axon site hook pins ``jax_platforms`` at interpreter start, which
    outranks the env var — so a CPU smoke run of a benchmark would silently
    target the (possibly dead, hanging) TPU relay. No-op when the env var is
    unset or the backend is already initialized."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platforms)
    except Exception:  # backend already live: the request can't apply
        pass


def str_to_bool(value: str) -> int:
    """Convert a string to a bool int, accepting y/yes/t/true/on/1 (case-insensitive).

    Same contract as the reference's ``str_to_bool``
    (reference: utils/environment.py:60-75).
    """
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value}")


def get_int_from_env(env_keys, default: int) -> int:
    """Return the first positive int found among ``env_keys``."""
    for e in env_keys:
        val = int(os.environ.get(e, -1))
        if val >= 0:
            return val
    return default


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, str(default))
    return bool(str_to_bool(value))


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def are_libraries_initialized(*library_names: str) -> list[str]:
    import sys

    return [lib for lib in library_names if lib in sys.modules.keys()]


@contextlib.contextmanager
def clear_environment() -> Iterator[None]:
    """Temporarily clear ``os.environ``, restoring it afterwards even on error.

    (reference: utils/environment.py:197-230)
    """
    cached = dict(os.environ)
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(cached)


@contextlib.contextmanager
def patch_environment(**kwargs: Any) -> Iterator[None]:
    """Temporarily set env vars (upper-cased keys), restoring previous values.

    (reference: utils/environment.py:233-262)
    """
    existing = {}
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in existing:
                os.environ[key] = existing[key]
            else:
                os.environ.pop(key, None)


def purge_accelerate_environment(func):
    """Decorator: run ``func`` with all ACCELERATE_*/PARALLELISM_CONFIG_* vars
    removed, restoring them afterwards (reference: utils/environment.py:417-523)."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        cached = {
            k: os.environ.pop(k)
            for k in list(os.environ)
            if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_"))
        }
        try:
            return func(*args, **kwargs)
        finally:
            for k in list(os.environ):
                if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
                    del os.environ[k]
            os.environ.update(cached)

    return wrapper


def get_cpu_count() -> int:
    return os.cpu_count() or 1


def set_numa_affinity(local_process_index: int, verbose: bool = False) -> None:
    """Bind this process to the NUMA node of its local index.

    The reference pins GPU processes to NUMA nodes
    (reference: utils/environment.py:263-360). On TPU hosts there is normally
    one process per host so this is a best-effort no-op unless numactl-style
    sysfs info is present.
    """
    try:
        nodes = sorted(
            int(d.replace("node", ""))
            for d in os.listdir("/sys/devices/system/node")
            if d.startswith("node")
        )
    except OSError:
        return
    if not nodes:
        return
    node = nodes[local_process_index % len(nodes)]
    cpus = []
    try:
        with open(f"/sys/devices/system/node/node{node}/cpulist") as f:
            for part in f.read().strip().split(","):
                if "-" in part:
                    lo, hi = part.split("-")
                    cpus.extend(range(int(lo), int(hi) + 1))
                elif part:
                    cpus.append(int(part))
        if cpus and hasattr(os, "sched_setaffinity"):
            os.sched_setaffinity(0, cpus)
    except OSError:
        return
