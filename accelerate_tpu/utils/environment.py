"""Environment-variable helpers.

Config flows through environment variables, same architectural decision as the
reference (reference: src/accelerate/utils/environment.py and SURVEY.md §1):
the launcher encodes choices as ``ACCELERATE_*`` / ``PARALLELISM_CONFIG_*``
vars, worker processes decode them.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Iterator


def honor_jax_platforms_env() -> None:
    """Re-assert an explicit ``JAX_PLATFORMS`` request through jax.config.

    The axon site hook pins ``jax_platforms`` at interpreter start, which
    outranks the env var — so a CPU smoke run of a benchmark would silently
    target the (possibly dead, hanging) TPU relay. No-op when the env var is
    unset or the backend is already initialized."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platforms)
    except Exception:  # backend already live: the request can't apply
        pass


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
    """``jax.shard_map`` across the API move.

    Newer jax exposes ``jax.shard_map(..., check_vma=, axis_names=)``; older
    releases only have ``jax.experimental.shard_map.shard_map(...,
    check_rep=, auto=)``. ``axis_names`` (manual axes) would map onto legacy
    ``auto=`` (its complement), but partial-manual lowers to a PartitionId op
    the CPU backend rejects, so the legacy path runs full-manual instead.

    Full-manual has a sharp edge: an out_spec that omits a size>1 mesh axis
    leaves the output marked partial over that axis, and mixing such an
    output with ordinary values in the same jit silently scales them by the
    axis size (GSPMD repartitions the replicated operand as if it were
    unreduced). The legacy path therefore injects one leading broadcast dim
    per omitted size>1 axis into each out_spec — making the replication
    explicit — and reduces the dims back off after the call."""
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    from jax.sharding import PartitionSpec

    check_rep = bool(check_vma)

    def spec_axes(s):
        axes = set()
        for entry in tuple(s):
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                axes.update(entry)
            else:
                axes.add(entry)
        return axes

    is_spec = lambda x: isinstance(x, PartitionSpec)
    spec_leaves, spec_treedef = jax.tree_util.tree_flatten(out_specs, is_leaf=is_spec)
    big = [a for a in mesh.axis_names if mesh.shape[a] > 1]
    extras = [tuple(a for a in big if a not in spec_axes(s)) for s in spec_leaves]
    if not any(extras):
        return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_rep)

    padded = jax.tree_util.tree_unflatten(
        spec_treedef,
        [PartitionSpec(*e, *tuple(s)) for s, e in zip(spec_leaves, extras)],
    )

    def _zip_outputs(out):
        """Pair each output leaf with its spec's injected-dim count."""
        leaves, treedef = jax.tree_util.tree_flatten(out)
        if len(spec_leaves) == 1:
            ks = [len(extras[0])] * len(leaves)
        elif len(leaves) == len(spec_leaves):
            ks = [len(e) for e in extras]
        else:
            raise ValueError(
                "shard_map_compat: out_specs structure does not match outputs"
            )
        return leaves, treedef, ks

    def wrapped(*args):
        import jax.numpy as jnp

        out = f(*args)
        leaves, treedef, ks = _zip_outputs(out)
        leaves = [
            jnp.broadcast_to(o, (1,) * k + jnp.shape(o)) for o, k in zip(leaves, ks)
        ]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    mapped = legacy(wrapped, mesh=mesh, in_specs=in_specs, out_specs=padded,
                    check_rep=check_rep)

    def _strip(o, k):
        """Remove the injected leading dims by *reduction*, not slicing: a
        slice of a sharded dim can lower to mask+all-reduce, which is the
        very partial-sum pathway being worked around. All slices along the
        injected dims hold identical values, so mean (floats; its VJP splits
        the cotangent, and the broadcast transpose re-sums it) or max (ints,
        bools — no autodiff) recovers the value through a genuine reduction."""
        import jax.numpy as jnp

        if k == 0:
            return o
        axes = tuple(range(k))
        if jnp.issubdtype(jnp.result_type(o), jnp.inexact):
            return jnp.mean(o, axis=axes)
        return jnp.max(o, axis=axes)

    def call(*args):
        out = mapped(*args)
        leaves, treedef, ks = _zip_outputs(out)
        leaves = [_strip(o, k) for o, k in zip(leaves, ks)]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    return call


def str_to_bool(value: str) -> int:
    """Convert a string to a bool int, accepting y/yes/t/true/on/1 (case-insensitive).

    Same contract as the reference's ``str_to_bool``
    (reference: utils/environment.py:60-75).
    """
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value}")


def get_int_from_env(env_keys, default: int) -> int:
    """Return the first positive int found among ``env_keys``."""
    for e in env_keys:
        val = int(os.environ.get(e, -1))
        if val >= 0:
            return val
    return default


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, str(default))
    return bool(str_to_bool(value))


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def are_libraries_initialized(*library_names: str) -> list[str]:
    import sys

    return [lib for lib in library_names if lib in sys.modules.keys()]


@contextlib.contextmanager
def clear_environment() -> Iterator[None]:
    """Temporarily clear ``os.environ``, restoring it afterwards even on error.

    (reference: utils/environment.py:197-230)
    """
    cached = dict(os.environ)
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(cached)


@contextlib.contextmanager
def patch_environment(**kwargs: Any) -> Iterator[None]:
    """Temporarily set env vars (upper-cased keys), restoring previous values.

    (reference: utils/environment.py:233-262)
    """
    existing = {}
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in existing:
                os.environ[key] = existing[key]
            else:
                os.environ.pop(key, None)


def purge_accelerate_environment(func):
    """Decorator: run ``func`` with all ACCELERATE_*/PARALLELISM_CONFIG_* vars
    removed, restoring them afterwards (reference: utils/environment.py:417-523)."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        cached = {
            k: os.environ.pop(k)
            for k in list(os.environ)
            if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_"))
        }
        try:
            return func(*args, **kwargs)
        finally:
            for k in list(os.environ):
                if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
                    del os.environ[k]
            os.environ.update(cached)

    return wrapper


def get_cpu_count() -> int:
    return os.cpu_count() or 1


def set_numa_affinity(local_process_index: int, verbose: bool = False) -> None:
    """Bind this process to the NUMA node of its local index.

    The reference pins GPU processes to NUMA nodes
    (reference: utils/environment.py:263-360). On TPU hosts there is normally
    one process per host so this is a best-effort no-op unless numactl-style
    sysfs info is present.
    """
    try:
        nodes = sorted(
            int(d.replace("node", ""))
            for d in os.listdir("/sys/devices/system/node")
            if d.startswith("node")
        )
    except OSError:
        return
    if not nodes:
        return
    node = nodes[local_process_index % len(nodes)]
    cpus = []
    try:
        with open(f"/sys/devices/system/node/node{node}/cpulist") as f:
            for part in f.read().strip().split(","):
                if "-" in part:
                    lo, hi = part.split("-")
                    cpus.extend(range(int(lo), int(hi) + 1))
                elif part:
                    cpus.append(int(part))
        if cpus and hasattr(os, "sched_setaffinity"):
            os.sched_setaffinity(0, cpus)
    except OSError:
        return
