"""Feature probes.

The reference ships ~60 ``is_*_available()`` probes (reference:
src/accelerate/utils/imports.py). Here the core stack (jax/flax/optax/orbax)
is a hard dependency; probes cover the optional integrations (trackers,
safetensors, torch-interop, datasets).
"""

import functools
import importlib.metadata
import importlib.util


@functools.lru_cache(maxsize=None)
def _is_package_available(pkg_name: str) -> bool:
    if importlib.util.find_spec(pkg_name) is None:
        return False
    try:
        importlib.metadata.version(pkg_name)
    except importlib.metadata.PackageNotFoundError:
        # Namespace packages (or vendored modules) have no metadata but are
        # importable all the same.
        pass
    return True


def is_jax_available() -> bool:
    return _is_package_available("jax")


def is_flax_available() -> bool:
    return _is_package_available("flax")


def is_optax_available() -> bool:
    return _is_package_available("optax")


def is_orbax_available() -> bool:
    return _is_package_available("orbax")


def is_safetensors_available() -> bool:
    return _is_package_available("safetensors")


def is_torch_available() -> bool:
    return _is_package_available("torch")


def is_transformers_available() -> bool:
    return _is_package_available("transformers")


def is_datasets_available() -> bool:
    return _is_package_available("datasets")


def is_pandas_available() -> bool:
    return _is_package_available("pandas")


def is_rich_available() -> bool:
    return _is_package_available("rich")


def is_psutil_available() -> bool:
    return _is_package_available("psutil")


def is_yaml_available() -> bool:
    return _is_package_available("yaml")


# ---------------------------------------------------------------------------
# Trackers (reference: tracking.py:178-1246 — 9 integrations behind probes)
# ---------------------------------------------------------------------------

def is_tensorboard_available() -> bool:
    return _is_package_available("tensorboardX") or _is_package_available("tensorboard")


def is_wandb_available() -> bool:
    return _is_package_available("wandb")


def is_comet_ml_available() -> bool:
    return _is_package_available("comet_ml")


def is_aim_available() -> bool:
    return _is_package_available("aim")


def is_mlflow_available() -> bool:
    return _is_package_available("mlflow")


def is_clearml_available() -> bool:
    return _is_package_available("clearml")


def is_dvclive_available() -> bool:
    return _is_package_available("dvclive")


def is_swanlab_available() -> bool:
    return _is_package_available("swanlab")


def is_trackio_available() -> bool:
    return _is_package_available("trackio")


# ---------------------------------------------------------------------------
# Hardware probes
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def is_tpu_available(check_device: bool = True) -> bool:
    """True when a real TPU backend is attached to this process."""
    if not check_device:
        return True
    try:
        import jax

        return any(d.platform.startswith(("tpu", "axon")) for d in jax.devices())
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1
