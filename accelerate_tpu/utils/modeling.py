"""Model-placement utilities for big-model inference.

TPU-native rethink of the reference's ``utils/modeling.py`` (reference:
utils/modeling.py:227-2065). The reference mutates ``nn.Module`` objects,
moving individual ``nn.Parameter``s between devices
(``set_module_tensor_to_device``, utils/modeling.py:227-439). JAX separates
architecture from state, so here everything operates on *param pytrees*:

- abstract shapes come from ``jax.eval_shape`` (zero FLOPs, zero bytes — the
  role of meta-device init, reference: big_modeling.py:62-178);
- a *device map* assigns each named param group to a JAX device, ``"cpu"``
  (host RAM as numpy) or ``"disk"`` (numpy memmap, see utils/offload.py);
- checkpoint shards stream straight into their mapped placement so the full
  model never materializes in host or device memory at once (the role of
  ``load_checkpoint_in_model``, reference: utils/modeling.py:1805-2065).
"""

from __future__ import annotations

import json
import os
import re
from collections import defaultdict
from typing import Any, Mapping, Optional, Union

import jax
import numpy as np

from .other import convert_bytes, flatten_state_dict, parse_bytes, unflatten_state_dict

# A placement is a jax.Device, "cpu" (host numpy) or "disk" (memmap).
Placement = Union[jax.Device, str]


# ---------------------------------------------------------------------------
# Abstract (meta) initialization
# ---------------------------------------------------------------------------


def compute_abstract_params(module, *sample_args, rng=None, **sample_kwargs):
    """Shapes/dtypes of ``module.init`` without allocating anything.

    The reference patches ``nn.Module.register_parameter`` to land params on
    the meta device (big_modeling.py:62-178); ``jax.eval_shape`` is the
    first-class equivalent: tracing ``init`` yields a pytree of
    ``jax.ShapeDtypeStruct``.
    """
    if rng is None:
        rng = jax.random.key(0)
    variables = jax.eval_shape(lambda: module.init(rng, *sample_args, **sample_kwargs))
    return variables["params"]


def named_parameter_shapes(abstract_params, sep: str = "/") -> dict[str, jax.ShapeDtypeStruct]:
    """Flat {"path/to/param": ShapeDtypeStruct} view of an abstract tree."""
    flat = {}

    def _walk(prefix, node):
        if isinstance(node, Mapping):
            for k in sorted(node):
                _walk(f"{prefix}{sep}{k}" if prefix else k, node[k])
        else:
            flat[prefix] = node

    _walk("", abstract_params)
    return flat


def dtype_byte_size(dtype) -> float:
    """Bytes per element, supporting sub-byte dtypes (int4)."""
    dtype = np.dtype(dtype) if not hasattr(dtype, "itemsize") else dtype
    name = getattr(dtype, "name", str(dtype))
    if "int4" in name or "uint4" in name:
        return 0.5
    return dtype.itemsize


def tensor_bytes(t) -> int:
    return int(np.prod(t.shape) * dtype_byte_size(t.dtype)) if t.shape else int(dtype_byte_size(t.dtype))


def compute_module_sizes(abstract_params, dtype=None, sep: str = "/") -> dict[str, int]:
    """Bytes per module prefix, including ``""`` for the whole model.

    Mirrors reference utils/modeling.py:718-772: every ancestor prefix of a
    param accumulates its size, so the map can be queried at any granularity.
    ``dtype`` overrides the stored dtype (the reference's load-time dtype cast).
    """
    sizes: dict[str, int] = defaultdict(int)
    for name, spec in named_parameter_shapes(abstract_params, sep=sep).items():
        size = int(np.prod(spec.shape) * dtype_byte_size(dtype or spec.dtype))
        sizes[""] += size
        parts = name.split(sep)
        for i in range(1, len(parts) + 1):
            sizes[sep.join(parts[:i])] += size
    return dict(sizes)


def calculate_maximum_sizes(abstract_params, sep: str = "/"):
    """(total_bytes, (largest_leaf_module_bytes, name)) — the two numbers the
    ``estimate-memory`` CLI reports (reference: commands/estimate.py:66-318)."""
    sizes = compute_module_sizes(abstract_params, sep=sep)
    total = sizes[""]
    leaf_names = named_parameter_shapes(abstract_params, sep=sep)
    modules = {sep.join(n.split(sep)[:-1]) or n: 0 for n in leaf_names}
    for m in modules:
        modules[m] = sizes.get(m, 0)
    biggest = max(modules.items(), key=lambda kv: kv[1]) if modules else ("", 0)
    return total, (biggest[1], biggest[0])


# ---------------------------------------------------------------------------
# Memory budgets
# ---------------------------------------------------------------------------

_DEFAULT_HBM = 16 * 1024**3  # v5e chip when the backend exposes no stats


def get_max_memory(max_memory: Optional[dict] = None) -> dict[Any, int]:
    """{device_index: bytes, "cpu": bytes} budget map.

    Like reference utils/modeling.py:828-930 but reading HBM from the JAX
    device API (``memory_stats()["bytes_limit"]``) instead of
    ``torch.cuda.mem_get_info``. User entries accept "10GiB"-style strings.
    """
    if max_memory is not None:
        return {k: parse_bytes(v) if isinstance(v, (str, int)) else v for k, v in max_memory.items()}
    out: dict[Any, int] = {}
    for i, d in enumerate(jax.local_devices()):
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        limit = (stats or {}).get("bytes_limit", _DEFAULT_HBM)
        # Keep ~10% headroom for XLA scratch, like the reference's 90% rule.
        out[i] = int(limit * 0.9)
    try:
        cpu_bytes = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        cpu_bytes = 32 * 1024**3
    out["cpu"] = int(cpu_bytes * 0.9)
    return out


def get_balanced_memory(
    abstract_params,
    max_memory: Optional[dict] = None,
    no_split_modules: Optional[list[str]] = None,
    dtype=None,
    low_zero: bool = False,
) -> dict[Any, int]:
    """Even out per-device budgets so layers spread instead of greedily filling
    device 0 (reference: utils/modeling.py:931-1066). ``low_zero`` keeps
    device 0 light for generation-time KV-cache/IO headroom."""
    max_memory = get_max_memory(max_memory)
    devices = [k for k in max_memory if k not in ("cpu", "disk")]
    if len(devices) <= 1:
        return max_memory
    sizes = compute_module_sizes(abstract_params, dtype=dtype)
    n = len(devices) - (1 if low_zero else 0)
    per_device = sizes[""] // n
    # Leave room for the largest indivisible block on each device. Same
    # matching rule as infer_auto_device_map: regex fullmatch (or equality)
    # on the last path segment.
    leaves = [
        sizes[m]
        for m in sizes
        if m
        and no_split_modules
        and any(
            re.fullmatch(pat, m.split("/")[-1]) or m.split("/")[-1] == pat
            for pat in no_split_modules
        )
    ]
    if not leaves:
        # No no-split match: reserve the largest *leaf-parent* module (the
        # deepest grouping that directly holds params — e.g. one transformer
        # block), not a top-level module which is nearly the whole model.
        # Uses `sizes` so the ``dtype`` override applies here too.
        leaf_parents = {
            "/".join(n.split("/")[:-1]) or n
            for n in named_parameter_shapes(abstract_params)
        }
        leaves = [sizes.get(p, 0) for p in leaf_parents]
    buffer = max(leaves)
    target = per_device + buffer
    out = dict(max_memory)
    for d in devices:
        cap = 0 if (low_zero and d == devices[0]) else target
        out[d] = min(max_memory[d], cap) if cap else max_memory[d]
    if low_zero:
        out[devices[0]] = min(max_memory[devices[0]], buffer)
    return out


# ---------------------------------------------------------------------------
# Device-map inference
# ---------------------------------------------------------------------------


def infer_auto_device_map(
    abstract_params,
    max_memory: Optional[dict] = None,
    no_split_modules: Optional[list[str]] = None,
    dtype=None,
    offload_buffers: bool = False,
    sep: str = "/",
) -> dict[str, Placement]:
    """Greedy top-down packing of param groups onto device budgets.

    The reference walks named modules in declaration order, filling GPU 0,
    then 1, …, then "cpu", then "disk" (utils/modeling.py:1295-1602). Here
    groups are the pytree's nested prefixes; a group that doesn't fit on the
    current budget is split into its children unless its *name* matches
    ``no_split_modules`` (the ``no_split_module_classes`` role — flax scopes
    are named after their module class instances).
    """
    max_memory = get_max_memory(max_memory)
    no_split = no_split_modules or []
    budgets: list[tuple[Any, int]] = [
        (k, v) for k, v in max_memory.items() if k not in ("cpu", "disk")
    ]
    budgets.sort(key=lambda kv: kv[0])
    budgets.append(("cpu", max_memory.get("cpu", 0)))
    budgets.append(("disk", float("inf")))

    sizes = compute_module_sizes(abstract_params, dtype=dtype, sep=sep)
    device_map: dict[str, Placement] = {}
    cursor = 0
    remaining = [b for _, b in budgets]

    def _splittable(name: str, node) -> bool:
        if not isinstance(node, Mapping):
            return False
        leaf = name.split(sep)[-1]
        return not any(re.fullmatch(pat, leaf) or leaf == pat for pat in no_split)

    def _assign(name: str, node):
        nonlocal cursor
        size = sizes.get(name, 0)
        while cursor < len(remaining):
            if size <= remaining[cursor]:
                remaining[cursor] -= size
                device_map[name] = budgets[cursor][0]
                return
            if _splittable(name, node):
                for k in sorted(node):
                    _assign(f"{name}{sep}{k}", node[k])
                return
            cursor += 1
        raise MemoryError(f"Could not place module {name!r} ({convert_bytes(size)}) anywhere.")

    for k in sorted(abstract_params):
        _assign(k, abstract_params[k])
    # jax.Device placements instead of bare indices for device entries.
    return normalize_device_map(device_map)


def _covers(name: str, prefix: str, sep: str) -> bool:
    """A device-map prefix covers a param; "" is the match-all root entry."""
    return prefix == "" or name == prefix or name.startswith(prefix + sep)


def normalize_device_map(device_map: Mapping[str, Any]) -> dict[str, Any]:
    """Int placements → local jax devices (shared by dispatch/load paths)."""
    local = jax.local_devices()
    return {k: (local[v] if isinstance(v, int) else v) for k, v in device_map.items()}


def default_execution_device(device_map: Mapping[str, Any]):
    """First real device in the map, else the first local device."""
    devs = [d for d in device_map.values() if not isinstance(d, str)]
    return devs[0] if devs else jax.local_devices()[0]


def check_device_map(abstract_params, device_map: Mapping[str, Placement], sep: str = "/"):
    """Every param must be covered by exactly one device-map prefix
    (reference: utils/modeling.py:1604-1639)."""
    names = list(named_parameter_shapes(abstract_params, sep=sep))
    for n in names:
        hits = [p for p in device_map if _covers(n, p, sep)]
        if len(hits) == 0:
            raise ValueError(f"Param {n!r} not covered by device_map")
        if len(hits) > 1:
            # Nested prefixes: the longest match wins; overlap of distinct
            # non-nested prefixes is a config error.
            hits.sort(key=len)
            for a, b in zip(hits, hits[1:]):
                if a != "" and not b.startswith(a + sep) and a != b:
                    raise ValueError(f"Param {n!r} covered by overlapping entries {hits}")


def placement_for(name: str, device_map: Mapping[str, Placement], sep: str = "/") -> Placement:
    """Longest-prefix lookup of a param's placement."""
    best, best_len = None, -1
    for prefix, placement in device_map.items():
        if _covers(name, prefix, sep) and len(prefix) > best_len:
            best, best_len = placement, len(prefix)
    if best is None:
        raise KeyError(f"No device_map entry covers {name!r}")
    return best


# ---------------------------------------------------------------------------
# Placement + checkpoint streaming
# ---------------------------------------------------------------------------


def place_tensor(array: np.ndarray, placement: Placement, target_dtype=None):
    """The ``set_module_tensor_to_device`` role (reference:
    utils/modeling.py:227-439): land one weight in its mapped home."""
    if target_dtype is not None:
        array = np.asarray(array).astype(target_dtype) if array.dtype != target_dtype else array
    if placement == "cpu":
        return np.asarray(array)
    if placement == "disk":
        return array  # caller routes to the offload store
    return jax.device_put(array, placement)


def load_checkpoint_in_model(
    abstract_params,
    checkpoint: str,
    device_map: Optional[Mapping[str, Placement]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    sep: str = "/",
):
    """Stream a (possibly sharded) safetensors checkpoint into placements.

    Returns ``(params_tree, disk_index)``: tree leaves are jax Arrays (device
    entries), numpy arrays ("cpu") or ``OffloadedWeight`` handles ("disk",
    backed by ``offload_folder``). Shards are read one at a time so peak host
    memory is one shard (reference: utils/modeling.py:1805-2065).
    """
    from .offload import offload_weight, save_offload_index

    shapes = named_parameter_shapes(abstract_params, sep=sep)
    if device_map is None:
        device_map = {"": jax.local_devices()[0]}
    check_device_map(abstract_params, device_map, sep=sep)

    index_file = os.path.join(checkpoint, "model.safetensors.index.json")
    if os.path.isdir(checkpoint) and os.path.isfile(index_file):
        with open(index_file) as f:
            index = json.load(f)
        shard_files = sorted(set(index["weight_map"].values()))
        shards = [os.path.join(checkpoint, s) for s in shard_files]
    elif os.path.isdir(checkpoint):
        shards = [
            os.path.join(checkpoint, f)
            for f in sorted(os.listdir(checkpoint))
            if f.endswith(".safetensors")
        ]
    else:
        shards = [checkpoint]

    flat_out: dict[str, Any] = {}
    disk_index: dict[str, dict] = {}
    from safetensors.numpy import load_file

    for shard in shards:
        loaded = load_file(shard)
        for name, arr in loaded.items():
            if name not in shapes:
                continue  # tolerated extra weight (reference warns + skips)
            placement = placement_for(name, device_map, sep=sep)
            want = shapes[name]
            if tuple(arr.shape) != tuple(want.shape):
                raise ValueError(
                    f"Checkpoint weight {name!r} has shape {tuple(arr.shape)} but the "
                    f"model expects {tuple(want.shape)}"
                )
            cast = dtype or want.dtype
            if arr.dtype != cast:
                arr = arr.astype(cast)
            if placement == "disk":
                if offload_folder is None:
                    raise ValueError("device_map contains 'disk' entries but no offload_folder given")
                disk_index[name] = offload_weight(arr, name, offload_folder)
                flat_out[name] = _DiskHandle(name, offload_folder, arr.shape, arr.dtype)
            else:
                flat_out[name] = place_tensor(arr, placement)
        del loaded
    missing = sorted(set(shapes) - set(flat_out))
    if missing:
        raise ValueError(f"Checkpoint {checkpoint} is missing weights: {missing[:8]}…")
    if disk_index:
        save_offload_index(disk_index, offload_folder)
    return unflatten_state_dict(flat_out, sep=sep), disk_index


class _DiskHandle:
    """Lazy leaf standing in for a disk-offloaded weight."""

    __slots__ = ("name", "folder", "shape", "dtype")

    def __init__(self, name, folder, shape, dtype):
        self.name, self.folder, self.shape, self.dtype = name, folder, shape, np.dtype(dtype)

    def load(self) -> np.ndarray:
        from .offload import load_offloaded_weight

        return load_offloaded_weight(
            self.folder, self.name, {"shape": list(self.shape), "dtype": self.dtype.name}
        )

    def __repr__(self):
        return f"_DiskHandle({self.name!r}, shape={self.shape}, dtype={self.dtype})"
