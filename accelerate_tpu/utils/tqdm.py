"""Rank-aware tqdm (reference: utils/tqdm.py — ``main_process_only`` bars).

``from accelerate_tpu.utils import tqdm`` draws the bar on the main process
only, so an N-process gang prints one bar instead of N interleaved ones.
"""

from __future__ import annotations

__all__ = ["tqdm"]


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """Drop-in ``tqdm.auto.tqdm`` that is silent off the main process.

    Accepts the same signature; ``main_process_only=False`` restores
    per-process bars. Requires the ``tqdm`` package (raise mirrors the
    reference's ImportError contract).
    """
    try:
        from tqdm.auto import tqdm as _tqdm
    except ImportError as e:  # pragma: no cover - tqdm is ubiquitous
        raise ImportError(
            "accelerate_tpu.utils.tqdm requires the tqdm package: pip install tqdm"
        ) from e

    if main_process_only:
        from ..state import PartialState

        kwargs.setdefault("disable", not PartialState().is_main_process)
    return _tqdm(*args, **kwargs)
