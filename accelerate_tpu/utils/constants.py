"""Framework-wide constants.

Mirrors the role of the reference's ``utils/constants.py`` (reference:
src/accelerate/utils/constants.py) but for a JAX/TPU runtime: no torch version
gates, instead checkpoint file layout names and env-var prefixes.
"""

MODEL_NAME = "model"
ORBAX_DIR_NAME = "distributed_state"  # DISTRIBUTED_STATE_DICT checkpoint subdir
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
DATALOADER_NAME = "dataloader"
RNG_STATE_NAME = "random_states"
PROFILE_PATTERN_NAME = "profile_{suffix}.json"

SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
WEIGHTS_NAME = "model.msgpack"
WEIGHTS_INDEX_NAME = "model.msgpack.index.json"
OFFLOAD_INDEX_NAME = "offload_index.json"

# Maximum shard size for `save_model` safetensors export (same contract as the
# reference's 5GB sharding, accelerator.py:3439).
MAX_SHARD_SIZE = "5GB"

# Env-var prefixes (kept byte-compatible with the reference where sensible —
# reference: utils/launch.py:201-427).
ACCELERATE_ENV_PREFIX = "ACCELERATE_"
PARALLELISM_CONFIG_PREFIX = "PARALLELISM_CONFIG_"
FSDP_ENV_PREFIX = "FSDP_"

# Canonical mesh axis names, in the reference's canonical order
# (reference: parallelism_config.py:211-272). ``pp`` and ``ep`` are
# first-class here (the reference only reaches them through Megatron-LM).
MESH_AXIS_ORDER = ("dp_replicate", "dp_shard", "cp", "sp", "tp")

# Flattened logical axis groups (tuples usable directly in PartitionSpec).
DP_AXES = ("dp_replicate", "dp_shard")
DP_SHARD_CP_AXES = ("dp_shard", "cp")
DP_CP_AXES = ("dp_replicate", "dp_shard", "cp")
BATCH_AXES = ("dp_replicate", "dp_shard", "cp", "sp")

ELASTIC_LOG_PREFIX = "[accelerate-tpu]"

SCALER_NAME = "scaler"

# Fault tolerance (fault_tolerance.py). An automatic checkpoint dir matches
# CHECKPOINT_DIR_REGEX; anything else under <project>/checkpoints (stray user
# dirs, interrupted ".tmp" staging dirs) is skipped by the load resolver and
# the total_limit pruner. Atomic saves stage into "<final>" +
# CHECKPOINT_STAGING_SUFFIX and rename on commit; CHECKPOINT_MANIFEST_NAME
# inside a committed dir carries per-file sizes/checksums + step + world size.
CHECKPOINT_DIR_REGEX = r"^checkpoint_(\d+)$"
CHECKPOINT_STAGING_SUFFIX = ".tmp"
CHECKPOINT_MANIFEST_NAME = "manifest.json"
# Elastic resharding (resharding.py): a sidecar written next to the model
# files recording the SOURCE topology — mesh layout + per-leaf sharding
# specs — so a restore onto a different mesh can plan a redistribution
# schedule instead of failing on the shape/world-size mismatch.
PLAN_MANIFEST_NAME = "plan_manifest.json"
# Exit code a preemption-triggered save exits with (BSD EX_TEMPFAIL): the
# launch gang loop treats it as "resumable — relaunch with
# ACCELERATE_RESTART_ATTEMPT+1" instead of a crash.
PREEMPTION_EXIT_CODE = 75
# Exit code the step watchdog's self-preempt escalation hard-exits with when
# the loop is too stuck to take the SIGTERM save path (fault_tolerance.py
# StepWatchdog). The launch supervisor classifies it "stalled" — resumable
# from the newest verified checkpoint, counted against the restart budget.
TRAINING_STALLED_EXIT_CODE = 76
# Exit code for "the divergence is reproducible from the checkpoint"
# (DivergenceError after max_rollbacks). The supervisor refuses to relaunch:
# the same checkpoint feeds the same divergence, so a restart would thrash.
POISONED_CHECKPOINT_EXIT_CODE = 77
# Exit code a hard serving-engine death exits with (the chaos ``engine_crash``
# default — serving.py). The launch supervisor classifies it "serving-crash"
# and relaunches with ZERO backoff: the request journal (journal.py) makes a
# relaunch immediately productive, so waiting only burns SLO budget.
SERVING_CRASH_EXIT_CODE = 78
