"""Framework-wide constants.

Mirrors the role of the reference's ``utils/constants.py`` (reference:
src/accelerate/utils/constants.py) but for a JAX/TPU runtime: no torch version
gates, instead checkpoint file layout names and env-var prefixes.
"""

MODEL_NAME = "model"
ORBAX_DIR_NAME = "distributed_state"  # DISTRIBUTED_STATE_DICT checkpoint subdir
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
DATALOADER_NAME = "dataloader"
RNG_STATE_NAME = "random_states"
PROFILE_PATTERN_NAME = "profile_{suffix}.json"

SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
WEIGHTS_NAME = "model.msgpack"
WEIGHTS_INDEX_NAME = "model.msgpack.index.json"
OFFLOAD_INDEX_NAME = "offload_index.json"

# Maximum shard size for `save_model` safetensors export (same contract as the
# reference's 5GB sharding, accelerator.py:3439).
MAX_SHARD_SIZE = "5GB"

# Env-var prefixes (kept byte-compatible with the reference where sensible —
# reference: utils/launch.py:201-427).
ACCELERATE_ENV_PREFIX = "ACCELERATE_"
PARALLELISM_CONFIG_PREFIX = "PARALLELISM_CONFIG_"
FSDP_ENV_PREFIX = "FSDP_"

# Canonical mesh axis names, in the reference's canonical order
# (reference: parallelism_config.py:211-272). ``pp`` and ``ep`` are
# first-class here (the reference only reaches them through Megatron-LM).
MESH_AXIS_ORDER = ("dp_replicate", "dp_shard", "cp", "sp", "tp")

# Flattened logical axis groups (tuples usable directly in PartitionSpec).
DP_AXES = ("dp_replicate", "dp_shard")
DP_SHARD_CP_AXES = ("dp_shard", "cp")
DP_CP_AXES = ("dp_replicate", "dp_shard", "cp")
BATCH_AXES = ("dp_replicate", "dp_shard", "cp", "sp")

ELASTIC_LOG_PREFIX = "[accelerate-tpu]"

SCALER_NAME = "scaler"

# Fault tolerance (fault_tolerance.py). An automatic checkpoint dir matches
# CHECKPOINT_DIR_REGEX; anything else under <project>/checkpoints (stray user
# dirs, interrupted ".tmp" staging dirs) is skipped by the load resolver and
# the total_limit pruner. Atomic saves stage into "<final>" +
# CHECKPOINT_STAGING_SUFFIX and rename on commit; CHECKPOINT_MANIFEST_NAME
# inside a committed dir carries per-file sizes/checksums + step + world size.
CHECKPOINT_DIR_REGEX = r"^checkpoint_(\d+)$"
CHECKPOINT_STAGING_SUFFIX = ".tmp"
CHECKPOINT_MANIFEST_NAME = "manifest.json"
# Elastic resharding (resharding.py): a sidecar written next to the model
# files recording the SOURCE topology — mesh layout + per-leaf sharding
# specs — so a restore onto a different mesh can plan a redistribution
# schedule instead of failing on the shape/world-size mismatch.
PLAN_MANIFEST_NAME = "plan_manifest.json"
# ----------------------------------------------------------------------
# Exit-code protocol. Workers choose these codes ON PURPOSE (the protocol
# rows below); everything else the supervisor infers from POSIX conventions
# (negative rc = Popen killed-by-signal, 128+N = shell-style signal death).
# EXIT_CODE_TABLE is the single source of truth: commands/launch.py
# ``classify_exit`` resolves the protocol codes from it and the docs
# render their exit-code table from the same rows — tests/test_cli.py pins
# that table and classifier agree, so a new code added here without a
# classification (or vice versa) fails loudly.
# ----------------------------------------------------------------------

# BSD EX_TEMPFAIL: a preemption-triggered save exits with this; the launch
# gang loop treats it as "resumable — relaunch with
# ACCELERATE_RESTART_ATTEMPT+1" instead of a crash.
PREEMPTION_EXIT_CODE = 75
# The step watchdog's self-preempt escalation hard-exits with this when the
# loop is too stuck to take the SIGTERM save path (fault_tolerance.py
# StepWatchdog). Resumable from the newest verified checkpoint, counted
# against the restart budget.
TRAINING_STALLED_EXIT_CODE = 76
# "The divergence is reproducible from the checkpoint" (DivergenceError
# after max_rollbacks). The supervisor refuses to relaunch: the same
# checkpoint feeds the same divergence, so a restart would thrash.
POISONED_CHECKPOINT_EXIT_CODE = 77
# A hard serving-engine death (the chaos ``engine_crash`` default —
# serving.py). The supervisor relaunches with ZERO backoff: the request
# journal (journal.py) makes a relaunch immediately productive, so waiting
# only burns SLO budget.
SERVING_CRASH_EXIT_CODE = 78
# Sticky silent data corruption (sdc.py): the redundant-compute probe
# reproduced a wrong-but-finite digest on a golden batch, so the silicon —
# not the state — is bad. The host is quarantined on disk
# (SDC_QUARANTINE_FILE) and the supervisor relaunches SHRUNK with zero
# backoff, excluding it; elastic resume reshards the newest verified
# checkpoint onto the smaller gang.
SDC_EXIT_CODE = 79
# A whole serving CELL died under a fleet router (fleet.py): its engine
# stopped making progress (max_idle_ticks) or its process exited. The
# router already drained the cell's journal onto survivors exactly-once,
# so a cell supervisor relaunches the cell with ZERO backoff — the WAL
# adoption sentinel (journal.py) keeps the relaunch from re-draining what
# the router already took.
CELL_DEAD_EXIT_CODE = 80
# The fleet itself is degraded: every cell is breaching its queue-depth
# band (router-level shed) or no healthy cell remains to drain onto. More
# capacity, not a faster restart, is the fix — relaunch with backoff.
FLEET_DEGRADED_EXIT_CODE = 81

EXIT_CODE_TABLE = (
    # (code, constant, classification, supervisor response)
    {"code": 0, "constant": None, "classification": "ok",
     "response": "stop — clean exit"},
    {"code": PREEMPTION_EXIT_CODE, "constant": "PREEMPTION_EXIT_CODE",
     "classification": "preempted",
     "response": "relaunch with zero backoff; elastic resume restores the "
                 "preemption auto-save"},
    {"code": TRAINING_STALLED_EXIT_CODE, "constant": "TRAINING_STALLED_EXIT_CODE",
     "classification": "stalled",
     "response": "relaunch with backoff from the newest verified checkpoint"},
    {"code": POISONED_CHECKPOINT_EXIT_CODE,
     "constant": "POISONED_CHECKPOINT_EXIT_CODE",
     "classification": "poisoned",
     "response": "refuse — a relaunch replays the same divergence"},
    {"code": SERVING_CRASH_EXIT_CODE, "constant": "SERVING_CRASH_EXIT_CODE",
     "classification": "serving-crash",
     "response": "relaunch with zero backoff; recover() replays the journal"},
    {"code": SDC_EXIT_CODE, "constant": "SDC_EXIT_CODE",
     "classification": "sdc",
     "response": "relaunch SHRUNK with zero backoff, quarantined host "
                 "excluded (persisted in the quarantine file)"},
    {"code": CELL_DEAD_EXIT_CODE, "constant": "CELL_DEAD_EXIT_CODE",
     "classification": "cell-dead",
     "response": "relaunch the cell with zero backoff; the fleet router "
                 "already drained its journal onto survivors"},
    {"code": FLEET_DEGRADED_EXIT_CODE, "constant": "FLEET_DEGRADED_EXIT_CODE",
     "classification": "fleet-degraded",
     "response": "relaunch with backoff — every cell is breaching, more "
                 "capacity is the fix, not a faster restart"},
    {"code": 130, "constant": None, "classification": "interrupted",
     "response": "stop — the operator hit Ctrl-C"},
    {"code": 137, "constant": None, "classification": "oom",
     "response": "relaunch with backoff (kernel OOM kill)"},
    {"code": 139, "constant": "DEAD_HOST_DEFAULT_EXIT_CODE (chaos.py)",
     "classification": "dead-host",
     "response": "relaunch with backoff; --shrink_after_dead_hosts=K shrinks "
                 "after K consecutive deaths"},
)

# The protocol subset of the table: codes a worker EXITS WITH DELIBERATELY,
# which classify_exit resolves by exact lookup (the rest it infers from
# POSIX signal conventions).
PROTOCOL_EXIT_CLASSES = {
    row["code"]: row["classification"]
    for row in EXIT_CODE_TABLE
    if row["code"] in (PREEMPTION_EXIT_CODE, TRAINING_STALLED_EXIT_CODE,
                       POISONED_CHECKPOINT_EXIT_CODE, SERVING_CRASH_EXIT_CODE,
                       SDC_EXIT_CODE, CELL_DEAD_EXIT_CODE,
                       FLEET_DEGRADED_EXIT_CODE)
}

# On-disk quarantine record (sdc.py): written next to the checkpoints when a
# sticky-SDC probe convicts this host's silicon, read back by the next
# launch so the exclusion survives gang restarts.
SDC_QUARANTINE_FILE = "sdc_quarantine.json"

# Crash flight bundle (profiler.py FlightRecorder): the last-N-records ring
# dumped by every deliberate abnormal exit, named by the exit's
# EXIT_CODE_TABLE classification (flight_serving-crash.json, flight_sdc.json,
# ...). Written to $ACCELERATE_FLIGHT_DIR when set (the supervisor and its
# children agree on the env var), else the dying process's project dir/cwd —
# commands/launch.py surfaces the newest bundle after an abnormal child exit.
FLIGHT_RECORD_PATTERN = "flight_{exit_class}.json"
FLIGHT_DIR_ENV = "ACCELERATE_FLIGHT_DIR"
