"""Default config file locations (reference: commands/config/config_args.py)."""

import os


def cache_dir() -> str:
    return os.environ.get(
        "ACCELERATE_CONFIG_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "accelerate_tpu"),
    )


def default_config_file() -> str:
    return os.path.join(cache_dir(), "default_config.json")
