"""Miscellaneous utilities: save/load, byte formatting, pytree flattening.

Reference: src/accelerate/utils/other.py:248-547.
"""

from __future__ import annotations

import json
import os
import platform
import re
import socket
from typing import Any, Mapping

import jax
import numpy as np


def is_main_process_fn() -> bool:
    from ..state import PartialState

    return PartialState().is_main_process


# ---------------------------------------------------------------------------
# Pytree ↔ flat dict with "/"-joined string keys (the bridge between JAX
# param trees and safetensors' flat tensor-dict format).
# ---------------------------------------------------------------------------

def flatten_state_dict(tree, sep: str = "/") -> dict[str, np.ndarray]:
    """Flatten a pytree of arrays to ``{"path/to/leaf": ndarray}``.

    Param identity is by *name*, never object id — the design rule SURVEY.md §7
    hard-part 5 calls out (checkpoints must survive resharding and optimizer
    rebuilds)."""
    flat = {}

    def _walk(prefix, node):
        if isinstance(node, Mapping):
            for k, v in node.items():
                _walk(f"{prefix}{sep}{k}" if prefix else str(k), v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                _walk(f"{prefix}{sep}{i}" if prefix else str(i), v)
        elif node is None:
            return
        else:
            flat[prefix] = np.asarray(node)

    _walk("", tree)
    return flat


def unflatten_state_dict(flat: Mapping[str, Any], sep: str = "/") -> dict:
    """Inverse of :func:`flatten_state_dict` (all containers become dicts;
    integer-keyed levels stay string-keyed, matching how checkpoint loaders
    re-map by name)."""
    tree: dict = {}
    for key, value in flat.items():
        parts = key.split(sep)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


# ---------------------------------------------------------------------------
# safetensors export (reference: utils/other.py:384-433 + accelerator.py:3439,
# 5GB sharding with index json).
# ---------------------------------------------------------------------------

def save_safetensors(state_dict: Mapping[str, np.ndarray], path: str):
    from ..native import save_safetensors_fast

    # ascontiguousarray is load-bearing: on TPU np.asarray of a device array
    # can be a non-C-contiguous view (the device's tiled layout exposed as
    # strides), and safetensors serializes the raw buffer without honoring
    # strides — silently corrupting every such tensor on disk.
    host = {k: np.ascontiguousarray(np.asarray(v)) for k, v in state_dict.items()}
    # Parallel-pwrite native writer for big files (native/host_runtime.cpp
    # at_pwrite_segments); safetensors lib otherwise.
    if save_safetensors_fast(host, path):
        return
    from safetensors.numpy import save_file

    save_file(host, path)


def load_safetensors(path: str) -> dict[str, np.ndarray]:
    from ..native import load_safetensors_fast

    # Parallel-pread native reader for big files (native/host_runtime.cpp
    # at_pread_segments); safetensors lib otherwise.
    loaded = load_safetensors_fast(path)
    if loaded is not None:
        return loaded
    from safetensors.numpy import load_file

    return load_file(path)


def parse_bytes(size: str | int) -> int:
    """'5GB' → bytes (reference: utils/modeling.py convert_file_size_to_int)."""
    if isinstance(size, int):
        return size
    m = re.fullmatch(r"\s*([\d.]+)\s*([KMGT]?I?B?)\s*", size.upper())
    if not m:
        raise ValueError(f"Unparseable size {size!r}")
    num = float(m.group(1))
    unit = m.group(2)
    mult = {
        "B": 1, "": 1,
        "KB": 10**3, "KIB": 2**10,
        "MB": 10**6, "MIB": 2**20,
        "GB": 10**9, "GIB": 2**30,
        "TB": 10**12, "TIB": 2**40,
    }[unit]
    return int(num * mult)


def convert_bytes(size: int) -> str:
    """Human-readable bytes (reference: utils/modeling.py:60-75)."""
    for unit in ["B", "KB", "MB", "GB", "TB"]:
        if abs(size) < 1024.0:
            return f"{size:.2f} {unit}"
        size /= 1024.0
    return f"{size:.2f} PB"


def shard_state_dict(
    state_dict: dict[str, np.ndarray], max_shard_size: str | int = "5GB", weights_name: str = "model.safetensors"
):
    """Split a flat state dict into ≤max_shard_size shards + index
    (reference contract: accelerator.py:3439-3551 via huggingface_hub
    split_torch_state_dict_into_shards)."""
    max_bytes = parse_bytes(max_shard_size)
    shards: list[dict] = [{}]
    shard_sizes = [0]
    for key, tensor in state_dict.items():
        nbytes = int(np.asarray(tensor).nbytes)
        if shard_sizes[-1] + nbytes > max_bytes and shard_sizes[-1] > 0:
            shards.append({})
            shard_sizes.append(0)
        shards[-1][key] = tensor
        shard_sizes[-1] += nbytes
    if len(shards) == 1:
        return {weights_name: shards[0]}, None
    name_root, ext = os.path.splitext(weights_name)
    named = {}
    index = {"metadata": {"total_size": sum(shard_sizes)}, "weight_map": {}}
    for i, shard in enumerate(shards):
        shard_name = f"{name_root}-{i + 1:05d}-of-{len(shards):05d}{ext}"
        named[shard_name] = shard
        for key in shard:
            index["weight_map"][key] = shard_name
    return named, index


def save_sharded_safetensors(
    state_dict: dict[str, np.ndarray], save_directory: str, max_shard_size: str | int = "5GB",
    weights_name: str = "model.safetensors",
):
    os.makedirs(save_directory, exist_ok=True)
    named, index = shard_state_dict(state_dict, max_shard_size, weights_name)
    for shard_name, shard in named.items():
        save_safetensors(shard, os.path.join(save_directory, shard_name))
    if index is not None:
        idx_path = os.path.join(save_directory, weights_name.replace(".safetensors", ".safetensors.index.json"))
        with open(idx_path, "w") as f:
            json.dump(index, f, indent=2)
    return sorted(named)


def load_sharded_safetensors(directory: str, weights_name: str = "model.safetensors") -> dict[str, np.ndarray]:
    index_path = os.path.join(directory, weights_name.replace(".safetensors", ".safetensors.index.json"))
    single = os.path.join(directory, weights_name)
    state: dict[str, np.ndarray] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        for shard_name in sorted(set(index["weight_map"].values())):
            state.update(load_safetensors(os.path.join(directory, shard_name)))
    elif os.path.exists(single):
        state.update(load_safetensors(single))
    else:
        raise FileNotFoundError(f"No {weights_name} or index found in {directory}")
    return state


# ---------------------------------------------------------------------------
# Misc (reference: utils/other.py:466-547)
# ---------------------------------------------------------------------------

def check_os_kernel():
    """Warn on Linux kernels < 5.5 (known socket perf issue the reference also
    warns about, utils/other.py:531-547)."""
    import logging

    info = platform.uname()
    if info.system != "Linux":
        return
    _, version, *_ = re.split(r"(\d+\.\d+\.\d+)", info.release)
    major, minor, _ = (int(x) for x in version.split("."))
    if (major, minor) < (5, 5):
        logging.getLogger(__name__).warning(
            f"Detected kernel version {version}, which is below the recommended minimum of 5.5.0; "
            "this can cause the process to hang. It is recommended to upgrade the kernel to 5.5.0 or higher."
        )


def get_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def merge_dicts(source: dict, destination: dict) -> dict:
    """Recursive dict merge (reference: utils/other.py helper)."""
    for key, value in source.items():
        if isinstance(value, dict):
            node = destination.setdefault(key, {})
            merge_dicts(value, node)
        else:
            destination[key] = value
    return destination


def extract_model_from_parallel(model, keep_fp32_wrapper: bool = True, recursive: bool = False):
    """Unwrap a prepared model back to the user's object
    (reference: utils/other.py:248-310). JAX prepare() does not mutate the
    user's module, so this simply unwraps our thin `PreparedModel` handle."""
    while hasattr(model, "_accelerate_original"):
        model = model._accelerate_original
    return model


def wait_for_everyone():
    from ..state import PartialState

    PartialState().wait_for_everyone()


def write_basic_config(mixed_precision: str = "no", save_location: str | None = None):
    """Write a minimal default config yaml, used by `accelerate config --default`
    (reference: utils/other.py:466-510)."""
    from .config_paths import default_config_file

    path = save_location or default_config_file()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    config = {
        "compute_environment": "LOCAL_MACHINE",
        "distributed_type": "MULTI_DEVICE",
        "mixed_precision": mixed_precision,
        "num_processes": 1,
        "use_cpu": False,
    }
    with open(path, "w") as f:
        json.dump(config, f, indent=2)
    return path
