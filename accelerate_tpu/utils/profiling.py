"""Schedule-driven jax.profiler sessions.

The reference builds a ``torch.profiler.profile`` from ``ProfileKwargs``
(reference: utils/dataclasses.py:486-601) with a step-based
wait/warmup/active/repeat schedule driven by ``prof.step()``. jax.profiler is
start/stop based; :class:`ProfileSession` reproduces the schedule on top of it
and adds device-memory snapshots.
"""

from __future__ import annotations

import os

import jax

from ..logging import get_logger

logger = get_logger(__name__)


class ProfileSession:
    """One ``accelerator.profile()`` context.

    Without ``schedule_option`` the whole context is traced. With it, call
    :meth:`step` once per training step; the session opens a trace at each
    active-window start and closes it after ``active`` steps, ``repeat``
    times (0 = unlimited), skipping ``skip_first`` then cycling
    (wait → warmup → active) — torch.profiler semantics.
    """

    def __init__(self, handler, trace_dir: str):
        self.handler = handler
        self.trace_dir = trace_dir
        sched = handler.schedule_option or {}
        self.scheduled = bool(sched)
        self.wait = int(sched.get("wait", 0))
        self.warmup = int(sched.get("warmup", 0))
        self.active = int(sched.get("active", 1))
        self.repeat = int(sched.get("repeat", 0))
        self.skip_first = int(sched.get("skip_first", 0))
        if self.scheduled and self.active <= 0:
            raise ValueError("schedule_option['active'] must be >= 1")
        self.step_num = 0
        self.cycles_done = 0
        self._tracing = False
        self.trace_dirs: list[str] = []

    # -- lifecycle ---------------------------------------------------------

    def enter(self):
        if not self.scheduled:
            self._start(self.trace_dir)
        elif self.skip_first == 0 and self.wait + self.warmup == 0:
            # First active window opens before any step() call arrives.
            self._start(os.path.join(self.trace_dir, "cycle_0"))

    def exit(self):
        if self._tracing:
            self._stop()

    def step(self):
        """Advance the schedule by one training step.

        ``step()`` is called AFTER each training step (torch.profiler
        convention), so window boundaries look one step ahead: the trace
        starts when the NEXT step is the cycle's first active step and stops
        right after the cycle's LAST active step completes — the active
        steps' device work is inside the window.
        """
        if not self.scheduled:
            return
        self.step_num += 1
        pos = self.step_num - self.skip_first  # completed non-skipped steps
        # pos == 0 must fall through: with wait+warmup == 0 the look-ahead
        # start for cycle_0 fires exactly there (enter() only covers
        # skip_first == 0).
        if pos < 0:
            return
        cycle_len = self.wait + self.warmup + self.active
        in_cycle = (pos - 1) % cycle_len
        if self._tracing and in_cycle == cycle_len - 1:
            self._stop()
        # Look ahead: 0-based index of the NEXT step is `pos`.
        nxt_cycle_idx = pos // cycle_len
        nxt_in_cycle = pos % cycle_len
        if self.repeat and nxt_cycle_idx >= self.repeat:
            return
        if not self._tracing and nxt_in_cycle == self.wait + self.warmup:
            self._start(os.path.join(self.trace_dir, f"cycle_{nxt_cycle_idx}"))

    # -- internals ---------------------------------------------------------

    def _start(self, path: str):
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        self._current_dir = path
        self._tracing = True

    def _stop(self):
        jax.profiler.stop_trace()
        self._tracing = False
        self.trace_dirs.append(self._current_dir)
        self.cycles_done += 1
        if self.handler.profile_memory:
            try:
                jax.profiler.save_device_memory_profile(
                    os.path.join(self._current_dir, "memory.prof")
                )
            except Exception as e:  # memory profiling needs a live backend
                logger.warning(f"device memory profile failed: {e}")
        if self.handler.on_trace_ready is not None:
            self.handler.on_trace_ready(self)
