"""Topology-aware per-chip memory estimation.

The reference's ``estimate-memory`` reports whole-model sizes per dtype
(reference: commands/estimate.py:66-318). The number a TPU user actually
needs is *per chip under a given ParallelismConfig*: will the 7B + Adam
working set fit 16 GB of v5e HBM at dp_shard=64? This module answers that
with the SAME sharding planner the trainer uses (parallel/sharding.py), so
the estimate and the training run can't drift apart:

- params / grads / optimizer moments: exact sharded bytes per chip, leaf by
  leaf, from :func:`plan_parameter_sharding` + :func:`infer_opt_state_sharding`
  over an :class:`~jax.sharding.AbstractMesh` (no devices needed — estimate a
  v5e-64 plan from a laptop).
- activations: a documented closed-form model of what the remat policy saves
  per scanned layer plus the recompute peak (approximate by nature; the
  tensor-state categories above are exact and dominate FSDP fit questions).

Used by ``accelerate-tpu estimate --parallelism ...`` and by the
``dryrun_7b_lowering`` scenario in ``__graft_entry__.py``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import AbstractMesh, NamedSharding

GiB = 1024 ** 3


def build_abstract_mesh(parallelism_config) -> AbstractMesh:
    """AbstractMesh with the trainer's canonical axis order (so the planner
    produces identical specs to ParallelismConfig.build_mesh's real mesh)."""
    import inspect

    from ..parallelism_config import MESH_AXIS_ORDER

    cfg = parallelism_config
    names = ("pp",) + MESH_AXIS_ORDER
    shape = (cfg.pp_size,) + tuple(cfg.axis_size(ax) for ax in MESH_AXIS_ORDER)
    # jax moved AbstractMesh from (axis_sizes, axis_names) to a single
    # ((name, size), ...) shape_tuple around 0.4.36; support both.
    if "shape_tuple" in inspect.signature(AbstractMesh.__init__).parameters:
        return AbstractMesh(tuple(zip(names, shape)))
    return AbstractMesh(shape, names)


def _shard_factor(sharding: NamedSharding, mesh) -> int:
    n = 1
    for entry in sharding.spec:
        if entry is None:
            continue
        for ax in entry if isinstance(entry, tuple) else (entry,):
            n *= mesh.shape[ax]
    return n


def _tree_bytes_per_chip(shapes: Any, shardings: Any, mesh, dtype=None) -> int:
    """Exact per-chip bytes of a sharded tree (shapes: ShapeDtypeStructs)."""
    total = 0
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(shapes),
        jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
        ),
    ):
        if not hasattr(leaf, "shape"):
            continue
        nbytes = math.prod(leaf.shape) * np.dtype(dtype or leaf.dtype).itemsize
        total += nbytes // _shard_factor(sh, mesh)
    return total


def replicated_large_leaves(shapes: Any, shardings: Any, mesh,
                            min_bytes: int = 2 ** 20) -> list[str]:
    """Leaves ≥ min_bytes whose sharding is fully replicated — the
    'involuntary replication' check for FSDP plans."""
    from ..parallel.sharding import _path_to_name

    bad = []

    def visit(path, leaf):
        sh = _sh_at(shardings, path)
        if (
            hasattr(leaf, "shape")
            and math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize >= min_bytes
            and _shard_factor(sh, mesh) == 1
        ):
            bad.append(_path_to_name(path))
        return leaf

    def _sh_at(tree, path):
        node = tree
        for p in path:
            key = getattr(p, "key", getattr(p, "idx", None))
            node = node[key]
        return node

    jax.tree_util.tree_map_with_path(visit, shapes)
    return bad


@dataclasses.dataclass
class MemoryEstimate:
    params_gib: float
    grads_gib: float
    opt_state_gib: float
    activations_gib: float
    logits_gib: float

    @property
    def total_gib(self) -> float:
        return (self.params_gib + self.grads_gib + self.opt_state_gib
                + self.activations_gib + self.logits_gib)

    def rows(self) -> list[tuple[str, float]]:
        return [
            ("params", self.params_gib),
            ("grads", self.grads_gib),
            ("optimizer state", self.opt_state_gib),
            ("activations (model)", self.activations_gib),
            ("loss/logits (model)", self.logits_gib),
            ("total", self.total_gib),
        ]


def _decoder_dims(cfg):
    """Field adapter: the builtin families name their dims differently
    (GPT-2: n_embd/n_head/n_layer; OPT/NeoX lack kv-heads or inter size)."""
    h = getattr(cfg, "hidden_size", None) or getattr(cfg, "n_embd")
    nh = getattr(cfg, "num_attention_heads", None) or getattr(cfg, "n_head")
    L = getattr(cfg, "num_hidden_layers", None) or getattr(cfg, "n_layer")
    nkv = getattr(cfg, "num_key_value_heads", None) or nh
    d = getattr(cfg, "head_dim", None) or h // nh
    inter = (getattr(cfg, "intermediate_size", None)
             or getattr(cfg, "n_inner", None)
             or getattr(cfg, "ffn_dim", None)
             or 4 * h)
    return h, nh, L, nkv, d, inter, cfg.vocab_size


def _activation_model(cfg, per_chip_batch: int, seq_local: int,
                      compute_bytes: int) -> tuple[int, int]:
    """(saved_bytes, logits_bytes) per chip for a scanned decoder.

    Model (documented, approximate): with ``remat`` on, each of the L layers
    saves its block input carry (B,S,H); policy "flash" additionally keeps the
    kernel's (out, lse); policy "dots" also keeps every matmul output
    (qkv/o/gate/up/down). The recompute peak is ~one block's working set.
    The fused chunked loss keeps one (B, chunk, V) fp32 logits slice live.
    Without remat every intermediate of every layer stays live — estimated as
    the "dots" footprint plus attention probabilities are never materialized
    (flash kernel), which is what the families compute.
    """
    H, nh, L, nkv, d, inter, vocab = _decoder_dims(cfg)
    B, S = per_chip_batch, seq_local
    c = compute_bytes

    carry = B * S * H * c
    flash_saved = B * S * nh * d * c + B * nh * S * 4  # kernel out + fp32 lse
    dots_saved = B * S * ((nh + 2 * nkv) * d + H + 2 * inter + inter) * c
    policy = getattr(cfg, "remat_policy", "flash")
    if getattr(cfg, "remat", False):
        if policy == "minimal":
            per_layer = carry
        elif policy == "dots":
            per_layer = carry + flash_saved + dots_saved
        else:  # flash
            per_layer = carry + flash_saved
        # Recompute peak: one block's full working set lives during backward.
        peak = dots_saved + flash_saved
    else:
        per_layer = carry + flash_saved + dots_saved
        peak = 0
    chunk = 256  # fused_cross_entropy_loss default
    logits = B * min(chunk, S) * vocab * 4  # fp32 softmax slice
    return per_layer * L + peak, logits


def activation_bytes(
    cfg,
    per_chip_batch: int,
    seq_local: int,
    compute_bytes: int,
    *,
    remat: Optional[bool] = None,
    remat_policy: Optional[str] = None,
) -> tuple[int, int]:
    """(saved_bytes, logits_bytes) of the closed-form activation model, with
    optional remat overrides so callers (the auto-parallelism planner's
    remat-escalation ladder, planner.py) can walk the none → selective →
    full ladder without rebuilding the module per rung."""
    if remat is not None or remat_policy is not None:
        cfg = dataclasses.replace(
            cfg,
            remat=cfg.remat if remat is None else remat,
            remat_policy=cfg.remat_policy if remat_policy is None else remat_policy,
        )
    return _activation_model(cfg, per_chip_batch, seq_local, compute_bytes)


def abstract_param_shapes(module) -> Any:
    """Abstract (ShapeDtypeStruct) param tree of ``module`` — one eval_shape,
    no FLOPs, no memory. Split out so the planner can score many candidate
    topologies against a single shape tree."""
    ids = jax.ShapeDtypeStruct((1, 8), np.int32)
    return jax.eval_shape(
        lambda r, i: module.init(r, i), jax.random.key(0), ids
    )["params"]


def estimate_per_chip(
    module,
    cfg,
    parallelism_config,
    *,
    seq: int,
    per_chip_batch: int = 1,
    optimizer: str = "adamw",
    master_dtype: Any = np.float32,
    moments_dtype: Any = None,
    fsdp_plugin=None,
    tp_rules: Optional[list] = None,
    mesh=None,
    param_shapes: Any = None,
) -> tuple[MemoryEstimate, Any, Any]:
    """Per-chip HBM estimate for training ``module`` under the given
    topology. Returns (estimate, param_shapes, param_shardings) so callers
    (the 7B dryrun, the auto-parallelism planner) can reuse the plan.

    ``mesh`` may be a real Mesh; defaults to an AbstractMesh built from
    ``parallelism_config`` — identical specs either way. ``param_shapes``
    skips the eval_shape when the caller already has the abstract tree
    (the planner scores dozens of topologies against one tree).
    """
    from ..parallel.sharding import infer_opt_state_sharding, plan_parameter_sharding

    mesh = mesh if mesh is not None else build_abstract_mesh(parallelism_config)
    shapes = param_shapes if param_shapes is not None else abstract_param_shapes(module)
    shardings = plan_parameter_sharding(
        shapes, mesh, fsdp_plugin=fsdp_plugin,
        parallelism_config=parallelism_config, tp_rules=tp_rules,
    )
    m_itemsize = np.dtype(master_dtype).itemsize
    params_b = _tree_bytes_per_chip(shapes, shardings, mesh, dtype=master_dtype)
    grads_b = params_b  # grads share the param specs + master dtype in the step

    moments = {"adamw": 2, "adam": 2, "sgd": 0, "momentum": 1, "lion": 1,
               "adafactor": 0}.get(optimizer, 2)
    mo_itemsize = np.dtype(moments_dtype or master_dtype).itemsize
    opt_b = params_b // m_itemsize * mo_itemsize * moments

    # Sequence is sharded over cp/sp; batch over dp axes is the caller's
    # per-chip number already.
    cfgp = parallelism_config
    seq_local = seq // max(1, cfgp.cp_size * cfgp.sp_size)
    compute_bytes = np.dtype(
        getattr(cfg, "dtype", np.dtype("bfloat16"))
    ).itemsize
    act_b, logits_b = _activation_model(cfg, per_chip_batch, seq_local, compute_bytes)

    est = MemoryEstimate(
        params_gib=params_b / GiB,
        grads_gib=grads_b / GiB,
        opt_state_gib=opt_b / GiB,
        activations_gib=act_b / GiB,
        logits_gib=logits_b / GiB,
    )
    return est, shapes, shardings
