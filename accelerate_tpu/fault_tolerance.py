"""Fault-tolerance subsystem (layer L11 — robustness).

Production pod-scale training runs on preemptible capacity: the scheduler
can SIGTERM the gang mid-``save_state``, storage can flake mid-write, and a
bad data shard can NaN a run hours before a human notices. Each failure mode
has a narrow, composable answer here:

- **Atomic verified checkpoints** — every save stages into
  ``checkpoint_N.tmp``, fsyncs, writes a ``manifest.json`` (per-file sizes +
  checksums + step + world size) and renames to ``checkpoint_N`` as the
  single commit point. A kill at ANY byte of the save leaves either the
  previous checkpoint set untouched or a ``.tmp`` dir the load resolver
  ignores. ``load_state()`` walks newest→oldest and restores the newest
  checkpoint whose manifest verifies, logging and skipping torn ones, and
  ``total_limit`` pruning runs *after* the commit so a failed save can never
  destroy the only good checkpoint.
- **Preemption-aware auto-save** — SIGTERM/SIGUSR1 handlers installed at
  ``prepare()`` (drained at ``end_training``) set a flag the loop observes
  via ``accelerator.should_checkpoint()`` / ``check_preemption()``; the loop
  then takes a final blocking save and exits with
  :data:`~accelerate_tpu.utils.constants.PREEMPTION_EXIT_CODE`, which the
  ``accelerate-tpu launch`` gang loop treats as *resumable* — the relaunch
  carries ``ACCELERATE_RESTART_ATTEMPT`` so elastic auto-resume continues
  from the preemption save with zero lost steps.
- **Save retry with backoff** — transient storage errors retry with
  jittered exponential backoff before falling back to a secondary directory.
- **Divergence sentinel** — watches each step's loss/grad-norm (fetched one
  step lagged, so the watch never stalls async dispatch) for K consecutive
  nonfinite or exploding steps, then applies policy ``warn | halt |
  rollback``; rollback restores the newest *verified* checkpoint and
  re-primes RNG/dataloader state so the run resumes deterministically.
- **Step watchdog** — large-scale practice (arXiv:2004.13336, PAPERS.md)
  shows step-time anomalies and silent hangs, not clean crashes, dominate:
  :class:`StepWatchdog` notes every completed step (same one-step-lag trick
  as the sentinel — the note itself never blocks dispatch) while a host
  thread polls the note's age. Past ``watchdog_warn_s`` it emits a
  ``training_stalled`` telemetry event naming the straggling rank with
  per-rank last-step ages; past ``watchdog_stall_s`` it escalates per
  policy: ``warn`` keeps logging, ``error`` raises
  :class:`TrainingStalledError` at the next completed step, ``preempt``
  SIGTERMs itself (the preemption save path, if the loop is alive) and
  hard-exits ``TRAINING_STALLED_EXIT_CODE`` after a grace period so the
  launch supervisor relaunches from the newest verified checkpoint. With
  ``watchdog_heartbeat_every`` > 0 a multi-process gang also allgathers
  (step, age) every N steps over the ``agree_any``-style channel, so a
  stalled PEER is detected rank-coherently.
- **Chaos injection** — a :class:`~accelerate_tpu.chaos.FaultInjector`
  passed as ``FaultToleranceKwargs(chaos=...)`` drives deterministic
  training faults through the SAME paths real ones take: ``nonfinite_grad``
  → sentinel → rollback, ``torn_write`` → save retry/backoff → fallback,
  ``corrupt_batch`` → a real NaN loss → rollback, ``slow_step`` → the
  watchdog's straggler ladder, ``dead_host`` → process exit → the launch
  supervisor's classify/backoff/relaunch. Replay the same seed and the
  fault schedule — and the recovery — reproduce exactly.

Default off: without a :class:`~accelerate_tpu.utils.FaultToleranceKwargs`
handler, ``accelerator.fault_tolerance`` is ``None``, every hook is a single
``None`` check, and the checkpoint byte layout is byte-identical to the
unmanaged path. All events (save retries, torn checkpoints skipped,
preemption saves, rollbacks) flow into the telemetry JSONL (telemetry.py)
when that subsystem is also enabled, so recovery actions are attributable
alongside step times.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import re
import shutil
import signal
import threading
import time
from typing import Callable, Optional

import numpy as np

from .logging import get_logger
from .utils.constants import (
    CHECKPOINT_DIR_REGEX,
    CHECKPOINT_MANIFEST_NAME,
    CHECKPOINT_STAGING_SUFFIX,
    POISONED_CHECKPOINT_EXIT_CODE,
    PREEMPTION_EXIT_CODE,
    TRAINING_STALLED_EXIT_CODE,
)

logger = get_logger(__name__)

_CKPT_RE = re.compile(CHECKPOINT_DIR_REGEX)

MANIFEST_VERSION = 1


class CheckpointSaveError(RuntimeError):
    """A checkpoint save failed after exhausting retries (and the fallback
    directory, when configured)."""


class DivergenceError(RuntimeError):
    """The divergence sentinel halted training (policy ``halt``, or
    ``rollback`` with no verified checkpoint / retries exhausted).
    ``exit_code`` is what a supervised training script should exit with:
    the launch supervisor classifies it poisoned-checkpoint and refuses to
    relaunch (the same checkpoint would reproduce the same divergence)."""

    exit_code = POISONED_CHECKPOINT_EXIT_CODE


class TrainingStalledError(RuntimeError):
    """The step watchdog (policy ``error``) detected a progress-free or
    straggling gang. Carries ``ages`` ({rank: seconds since that rank's
    last completed step}) and ``straggler`` (the most-behind rank).
    ``exit_code`` is what a supervised script should exit with: the launch
    supervisor classifies it stalled-but-resumable and relaunches from the
    newest verified checkpoint."""

    exit_code = TRAINING_STALLED_EXIT_CODE

    def __init__(self, msg: str, ages: Optional[dict] = None,
                 straggler: Optional[int] = None):
        super().__init__(msg)
        self.ages = dict(ages or {})
        self.straggler = straggler


def checkpoint_index(name: str) -> Optional[int]:
    """``checkpoint_12`` -> 12; anything else (``checkpoint_12.tmp``, a stray
    user dir) -> None. The single name-parsing point shared by the load
    resolver and the pruner — both previously crashed on non-numeric
    entries via ``int(f.split("_")[1])``."""
    m = _CKPT_RE.match(name)
    return int(m.group(1)) if m else None


def staging_path(final_dir: str) -> str:
    return final_dir + CHECKPOINT_STAGING_SUFFIX


# ---------------------------------------------------------------------------
# Manifest: write / verify
# ---------------------------------------------------------------------------


def _iter_checkpoint_files(root: str):
    """Relative paths of every regular file under ``root`` (sorted for a
    stable manifest), excluding the manifest itself."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            if rel == CHECKPOINT_MANIFEST_NAME:
                continue
            out.append(rel)
    return sorted(out)


def _file_sha256(path: str, chunk: int = 4 * 1024 * 1024) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_manifest(
    ckpt_dir: str, step: Optional[int], world_size: int, checksum: str = "sha256"
) -> dict:
    """Hash + fsync every file in ``ckpt_dir`` and durably write
    ``manifest.json``. The manifest is the LAST file written: its presence
    (inside a committed, renamed dir) certifies every byte it lists."""
    files = {}
    for rel in _iter_checkpoint_files(ckpt_dir):
        path = os.path.join(ckpt_dir, rel)
        entry = {"size": os.path.getsize(path)}
        if checksum == "sha256":
            entry["sha256"] = _file_sha256(path)
        files[rel] = entry
        _fsync_file(path)
    manifest = {
        "version": MANIFEST_VERSION,
        "step": step,
        # The monotonic guard weight-publication consumers key on (publish.py
        # refuses stale/duplicate versions) — the train step, when known.
        "weights_version": int(step) if step is not None else None,
        "world_size": world_size,
        "checksum": checksum,
        "time": time.time(),
        "files": files,
    }
    mpath = os.path.join(ckpt_dir, CHECKPOINT_MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(ckpt_dir)
    return manifest


def verify_checkpoint(ckpt_dir: str, check_hashes: bool = True) -> tuple[bool, str]:
    """Validate ``ckpt_dir`` against its manifest. Returns ``(ok, reason)``;
    ``reason`` is ``"no-manifest"`` for legacy (pre-fault-tolerance) dirs so
    callers can choose whether to trust them."""
    mpath = os.path.join(ckpt_dir, CHECKPOINT_MANIFEST_NAME)
    if not os.path.exists(mpath):
        return False, "no-manifest"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest ({e})"
    files = manifest.get("files")
    if not isinstance(files, dict):
        return False, "malformed manifest (no files map)"
    for rel, entry in files.items():
        path = os.path.join(ckpt_dir, rel)
        if not os.path.exists(path):
            return False, f"missing file {rel}"
        size = os.path.getsize(path)
        if size != entry.get("size"):
            return False, f"size mismatch for {rel} ({size} != {entry.get('size')})"
        want = entry.get("sha256")
        if check_hashes and want is not None:
            got = _file_sha256(path)
            if got != want:
                return False, f"checksum mismatch for {rel}"
    return True, "ok"


# ---------------------------------------------------------------------------
# Divergence sentinel (policy-only core — unit-testable without a mesh)
# ---------------------------------------------------------------------------


class DivergenceSentinel:
    """Streak detector over (loss, grad_norm) samples. Pure host-side state:
    feed it floats, it answers ``"ok" | "warn" | "trip"``. The manager maps
    ``trip`` onto the configured policy."""

    def __init__(self, window: int, explode_factor: float, ema_alpha: float):
        self.window = window
        self.explode_factor = explode_factor
        self.ema_alpha = ema_alpha
        self.ema_loss: Optional[float] = None
        self.streak = 0
        self.episode_warned = False

    def classify(self, loss: Optional[float], grad_norm: Optional[float]) -> tuple[bool, str]:
        """Is this sample bad, and why."""
        if loss is not None and not np.isfinite(loss):
            return True, f"nonfinite loss {loss}"
        if grad_norm is not None and not np.isfinite(grad_norm):
            return True, f"nonfinite grad norm {grad_norm}"
        if (
            loss is not None
            and self.ema_loss is not None
            and abs(loss) > self.explode_factor * max(abs(self.ema_loss), 1e-8)
        ):
            return True, (
                f"loss {loss:.4g} exploded past {self.explode_factor:g}x "
                f"EMA {self.ema_loss:.4g}"
            )
        return False, ""

    def observe(self, loss: Optional[float], grad_norm: Optional[float]) -> tuple[str, str]:
        """Feed one sample; returns ``(verdict, reason)`` where verdict is
        ``"ok"``, ``"warn"`` (healthy sample after a bad streak reset, or a
        bad sample below the streak threshold) or ``"trip"`` (streak just
        reached the window)."""
        bad, reason = self.classify(loss, grad_norm)
        if not bad:
            if loss is not None:
                self.ema_loss = (
                    loss
                    if self.ema_loss is None
                    else self.ema_alpha * loss + (1 - self.ema_alpha) * self.ema_loss
                )
            self.streak = 0
            self.episode_warned = False
            return "ok", ""
        self.streak += 1
        if self.streak >= self.window:
            return "trip", reason
        return "warn", reason

    def reset(self):
        self.streak = 0
        self.episode_warned = False
        self.ema_loss = None


# ---------------------------------------------------------------------------
# Step watchdog
# ---------------------------------------------------------------------------


class StepWatchdog:
    """Detects a progress-free or straggling gang without ever blocking the
    step. Two detection paths share one escalation ladder:

    - a daemon thread polls the age of this rank's last step note every
      ``watchdog_poll_s`` (catches true hangs — the loop never gets to run
      detection code itself);
    - :meth:`note_step`, called from the lagged ``observe_step`` hook,
      catches a slow-but-completed step on the spot and raises the
      thread-flagged :class:`TrainingStalledError` under policy ``error``
      (a thread cannot raise into the main thread; a completed step is the
      first safe opportunity).

    With ``watchdog_heartbeat_every`` > 0 on a multi-process gang,
    :meth:`maybe_heartbeat` allgathers (step, age) across ranks every N
    steps — the main-thread collective all ranks reach together — so a
    stalled PEER is detected and named rank-coherently.

    Escalation (once per stall episode; a completed step re-arms):
    warn log + ``training_stalled`` event at ``warn_s`` → stall event at
    ``stall_s`` → per policy: ``warn`` nothing more, ``error`` raise at the
    next completed step, ``preempt`` SIGTERM self (the preemption-save path
    if the loop is alive) then hard-exit ``TRAINING_STALLED_EXIT_CODE``
    after ``grace_s`` more without progress.
    """

    def __init__(self, manager, handler):
        self.manager = manager
        self.policy = handler.watchdog
        self.warn_s = float(handler.watchdog_warn_s)
        self.stall_s = float(handler.watchdog_stall_s)
        self.poll_s = float(handler.watchdog_poll_s)
        self.heartbeat_every = int(handler.watchdog_heartbeat_every)
        self.grace_s = float(handler.watchdog_grace_s)
        self.warnings = 0
        self.stalls = 0
        self.escalations = 0
        self.straggler_events = 0
        self.heartbeats = 0
        self.last_ages: Optional[dict] = None
        self._last_note: Optional[float] = None
        self._last_step = -1
        self._episode_warned = False
        self._episode_stalled = False
        self._preempted_at: Optional[float] = None
        self._pending_error: Optional[TrainingStalledError] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._last_note = time.monotonic()
        self._thread = threading.Thread(
            target=self._poll_loop, name="accelerate-tpu-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.poll_s))

    def age(self, now: Optional[float] = None) -> float:
        if self._last_note is None:
            return 0.0
        return (now if now is not None else time.monotonic()) - self._last_note

    # -- main-thread hooks -------------------------------------------------

    def note_step(self, step: int) -> None:
        """One completed step. Raises the thread-flagged stall under policy
        ``error``; otherwise records a straggler episode the thread missed
        (slow step shorter than a poll tick) and re-arms the episode."""
        err, self._pending_error = self._pending_error, None
        if err is not None:
            self.escalations += 1
            raise err
        now = time.monotonic()
        age = self.age(now)
        if age > self.warn_s and not self._episode_warned:
            self._record(age, level="straggler", source="step")
        self._last_note = now
        self._last_step = int(step)
        self._episode_warned = False
        self._episode_stalled = False
        self._preempted_at = None

    def maybe_heartbeat(self, tick: int) -> None:
        """Every ``heartbeat_every`` steps: allgather (step, age) across the
        gang and escalate on the most-behind PEER. A collective — every rank
        must reach it at the same tick, which holds because every rank steps
        the same loop."""
        if not self.heartbeat_every or tick % self.heartbeat_every:
            return
        state = self.manager.accelerator.state
        if state.num_processes <= 1:
            return
        rank = state.process_index
        chaos = self.manager.chaos
        if chaos is not None:
            f = chaos.draw("collective_op", tick, unit=rank)
            if f is not None:  # slow_step: delay OUR heartbeat — peers see it
                self.manager._note_fault(f)
                time.sleep(float((f.extra or {}).get(
                    "seconds", chaos.slow_step_s)))
        try:
            table = state.allgather_host_floats(
                [float(self._last_step), self.age()]
            )
        except Exception as e:  # a failed probe must never kill training
            logger.warning(f"fault_tolerance: watchdog heartbeat failed: {e}")
            return
        self.heartbeats += 1
        steps = [int(s) for s in table[:, 0]]
        ages = [float(a) for a in table[:, 1]]
        self.last_ages = {r: round(a, 3) for r, a in enumerate(ages)}
        behind = max(ages)
        if behind <= self.warn_s:
            return
        straggler = ages.index(behind)
        level = "stall" if behind > self.stall_s else "straggler"
        self.straggler_events += 1
        self._emit(level, behind, source="heartbeat",
                   ages=self.last_ages, straggler=straggler, steps=steps)
        if level == "stall":
            self.stalls += 1
            msg = (
                f"gang heartbeat: rank {straggler} last completed a step "
                f"{behind:.1f}s ago (stall_s={self.stall_s:g}); per-rank "
                f"ages {self.last_ages}"
            )
            if self.policy == "error":
                self.escalations += 1
                raise TrainingStalledError(
                    msg, ages=self.last_ages, straggler=straggler
                )
            if self.policy == "preempt":
                # Every rank computed the same table — the whole gang takes
                # the same self-preempt decision, no extra collective needed.
                self.escalations += 1
                os.kill(os.getpid(), signal.SIGTERM)

    # -- detection / escalation (thread + main paths) ----------------------

    def _record(self, age: float, level: str, source: str) -> None:
        """First warn of a stall episode."""
        self._episode_warned = True
        self.warnings += 1
        rank = getattr(self.manager.accelerator, "process_index", 0)
        self._emit(level, age, source=source,
                   ages={rank: round(age, 3)}, straggler=rank)

    def _emit(self, level: str, age: float, source: str, ages: dict,
              straggler: int, steps: Optional[list] = None) -> None:
        self.last_ages = {int(r): float(a) for r, a in ages.items()}
        logger.warning(
            "fault_tolerance: training stalled (%s, via %s) — rank %d has "
            "not completed a step in %.2fs (last step %d; warn %gs / stall "
            "%gs; policy %s).",
            level, source, straggler, age, self._last_step,
            self.warn_s, self.stall_s, self.policy,
        )
        fields = dict(
            level=level, source=source, policy=self.policy,
            straggler=int(straggler), age_s=round(age, 3),
            last_step=self._last_step,
            ages_s={str(r): round(float(a), 3) for r, a in ages.items()},
        )
        if steps is not None:
            fields["rank_steps"] = steps
        self.manager._event("training_stalled", **fields)

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            age = self.age(now)
            if age <= self.warn_s:
                continue
            if not self._episode_warned:
                rank = getattr(self.manager.accelerator, "process_index", 0)
                self._episode_warned = True
                self.warnings += 1
                self._emit("straggler", age, source="thread",
                           ages={rank: round(age, 3)}, straggler=rank)
            if age > self.stall_s and not self._episode_stalled:
                self._episode_stalled = True
                self.stalls += 1
                rank = getattr(self.manager.accelerator, "process_index", 0)
                self._emit("stall", age, source="thread",
                           ages={rank: round(age, 3)}, straggler=rank)
                self._escalate(age, rank)
            if (
                self._preempted_at is not None
                and now - self._preempted_at > self.grace_s
                and self.age() > self.grace_s
            ):
                # The SIGTERM save path never ran — the loop is truly stuck
                # (e.g. blocked inside a collective). Flush what we can and
                # die with the code the supervisor reads as "stalled,
                # resume from the newest verified checkpoint".
                logger.error(
                    "fault_tolerance: watchdog grace period (%gs) expired "
                    "with no progress after self-preempt — hard exit %d.",
                    self.grace_s, TRAINING_STALLED_EXIT_CODE,
                )
                from .profiler import dump_flight

                dump_flight(
                    getattr(self.manager.accelerator, "telemetry", None),
                    TRAINING_STALLED_EXIT_CODE,
                    reason=f"watchdog grace expired after self-preempt "
                           f"(no progress for {age:.2f}s)")
                self.manager.flush_telemetry()
                os._exit(TRAINING_STALLED_EXIT_CODE)

    def _escalate(self, age: float, rank: int) -> None:
        if self.policy == "warn":
            return
        self.escalations += 1
        if self.policy == "error":
            # Threads cannot raise into the main thread; flag it and the
            # next completed step raises. A full hang never completes a
            # step — use policy "preempt" for that failure mode.
            self._pending_error = TrainingStalledError(
                f"rank {rank} stalled: no step completed in {age:.2f}s "
                f"(stall_s={self.stall_s:g})",
                ages={rank: round(age, 3)}, straggler=rank,
            )
        elif self.policy == "preempt":
            self._preempted_at = time.monotonic()
            os.kill(os.getpid(), signal.SIGTERM)

    def summary(self) -> dict:
        return {
            "policy": self.policy,
            "warnings": self.warnings,
            "stalls": self.stalls,
            "escalations": self.escalations,
            "straggler_events": self.straggler_events,
            "heartbeats": self.heartbeats,
            "last_ages_s": self.last_ages,
        }


# ---------------------------------------------------------------------------
# Manager
# ---------------------------------------------------------------------------


class FaultToleranceManager:
    """One per Accelerator, created when a
    :class:`~accelerate_tpu.utils.FaultToleranceKwargs` handler is passed;
    every hook site no-ops through a ``None`` check when absent."""

    def __init__(self, accelerator, handler):
        self.accelerator = accelerator
        self.handler = handler
        self.preempted = False
        self.preemption_signal: Optional[str] = None
        self._installed: dict[int, object] = {}  # signum -> previous handler
        self.sentinel = DivergenceSentinel(
            handler.sentinel_window,
            handler.sentinel_explode_factor,
            handler.sentinel_ema_alpha,
        )
        # Lagged metric fetch: the sentinel reads step N-1's loss while step
        # N dispatches, so the host never waits on an in-flight step.
        self._pending_metrics = None
        self.rollbacks_done = 0
        self.save_retries_total = 0
        # Chaos (chaos.py): a FaultInjector instance or its constructor
        # kwargs. Ticks are MONOTONIC call counters, never the training step
        # — a rollback rewinds the step but must not replay (re-fire) the
        # injected fault, or the run would rollback forever.
        chaos = handler.chaos
        if isinstance(chaos, dict):
            from .chaos import FaultInjector

            chaos = FaultInjector(**chaos)
        self.chaos = chaos
        # SDC sentinel (sdc.py): armed only by FaultToleranceKwargs(sdc=...);
        # every hook below is a single None check. Independent of the
        # divergence sentinel policy — silent corruption is finite-but-wrong,
        # invisible to nonfinite checks.
        sdc = handler.sdc
        if sdc is not None:
            from .sdc import SDCConfig, SDCSentinel

            if isinstance(sdc, dict):
                sdc = SDCConfig(**sdc)
            sdc = SDCSentinel(self, sdc)
        self.sdc = sdc
        self.faults_injected = 0
        self._step_ticks = 0
        self._save_ticks = 0
        self._batch_ticks = 0
        # Step watchdog: armed at prepare() (start_watchdog), torn down in
        # close().
        self.watchdog: Optional[StepWatchdog] = None
        if handler.watchdog != "off":
            self.watchdog = StepWatchdog(self, handler)
        self._last_verified_dir: Optional[str] = None
        # Staging dirs save_state already cleared and seeded (pre-hook
        # sidecar files): save_accelerator_state must NOT re-wipe these as
        # stale leftovers.
        self._prearmed_staging: set[str] = set()

    # -- telemetry bridge --------------------------------------------------

    def _event(self, event: str, **fields) -> None:
        tel = getattr(self.accelerator, "telemetry", None)
        if tel is not None:
            tel.record_event(event, **fields)

    def flush_telemetry(self) -> None:
        """Best-effort final telemetry write before an injected/forced
        process death, so the summary (fault + watchdog tallies) survives."""
        tel = getattr(self.accelerator, "telemetry", None)
        if tel is None:
            return
        try:
            tel.close()
        except Exception:  # pragma: no cover - dying anyway
            pass

    # -- chaos hooks -------------------------------------------------------

    def _note_fault(self, fault) -> None:
        self.faults_injected += 1
        logger.warning(
            "fault_tolerance: injected %s at %s (tick %d, unit %d)",
            fault.kind, fault.point, fault.tick, fault.unit,
        )
        self._event(
            "fault_injected", point=fault.point, kind=fault.kind,
            tick=fault.tick, unit=fault.unit,
        )

    def _chaos_train_step(self, tick: int) -> bool:
        """Per-step chaos draws. Returns True when the step's metrics must
        be NaN-poisoned (``nonfinite_grad`` — the sentinel sees a divergence;
        model state is untouched so the rollback replay stays bit-equal)."""
        from .chaos import DEAD_HOST_DEFAULT_EXIT_CODE, flush_injected_log

        rank = getattr(self.accelerator, "process_index", 0)
        f = self.chaos.draw("host_heartbeat", tick, unit=rank)
        if f is not None:  # dead_host: die like real hardware — no cleanup
            self._note_fault(f)
            code = int((f.extra or {}).get(
                "exit_code", DEAD_HOST_DEFAULT_EXIT_CODE))
            logger.error(
                "fault_tolerance: injected dead_host — exiting %d "
                "(tick %d, rank %d).", code, tick, rank,
            )
            # os._exit skips every atexit/finally, so the flight ring and
            # the injector's full injected log must reach disk here or the
            # post-mortem loses the fault schedule that killed the run.
            from .profiler import dump_flight

            flush_injected_log(
                self.chaos, getattr(self.accelerator, "telemetry", None))
            dump_flight(getattr(self.accelerator, "telemetry", None), code,
                        reason=f"injected dead_host on rank {rank} at "
                               f"tick {tick}")
            os._exit(code)
        poison = False
        f = self.chaos.draw("train_step", tick, unit=rank)
        if f is not None:
            self._note_fault(f)
            if f.kind == "slow_step":
                time.sleep(float((f.extra or {}).get(
                    "seconds", self.chaos.slow_step_s)))
            elif f.kind == "nonfinite_grad":
                poison = True
            elif f.kind == "bit_flip" and self.sdc is not None:
                # Silent corruption: the NEXT observed digest on this rank is
                # flipped finite-but-wrong (sdc.py folds it in at the lag
                # swap). No NaN, no poison — only the vote can see it.
                self.sdc.note_bit_flip(f)
        return poison

    def _chaos_save_attempt(self, tick: int, attempt: int) -> None:
        """checkpoint_save/torn_write draw, one per (save, attempt) — a torn
        first attempt retries clean, exercising the real backoff path."""
        if self.chaos is None:
            return
        f = self.chaos.draw("checkpoint_save", tick, unit=attempt)
        if f is not None:
            self._note_fault(f)
            from .chaos import InjectedFaultError

            raise InjectedFaultError(f)

    def draw_batch_fault(self):
        """dataloader_batch draw at the loader's device_put boundary; the
        loader NaN-poisons the batch on a fault (data_loader.py), producing
        a REAL divergence the sentinel must roll back."""
        if self.chaos is None:
            return None
        tick = self._batch_ticks
        self._batch_ticks += 1
        f = self.chaos.draw(
            "dataloader_batch", tick,
            unit=getattr(self.accelerator, "process_index", 0),
        )
        if f is not None:
            self._note_fault(f)
        return f

    def start_watchdog(self) -> None:
        if self.watchdog is not None:
            self.watchdog.start()

    # -- atomic commit -----------------------------------------------------

    @property
    def atomic(self) -> bool:
        return bool(self.handler.atomic_checkpoints)

    def prearm_staging(self, staging_dir: str) -> None:
        self._prearmed_staging.add(os.path.abspath(staging_dir))

    def consume_prearmed(self, staging_dir: str) -> bool:
        """True exactly once per prearm_staging() call for this dir."""
        path = os.path.abspath(staging_dir)
        if path in self._prearmed_staging:
            self._prearmed_staging.discard(path)
            return True
        return False

    def commit(self, staging_dir: str, final_dir: str, step: Optional[int]) -> None:
        """Main-process commit point: manifest + fsync + rename. Callers
        barrier around this."""
        t0 = time.perf_counter()
        write_manifest(
            staging_dir,
            step,
            self.accelerator.num_processes,
            checksum=self.handler.checksum,
        )
        if os.path.isdir(final_dir):
            # A same-name leftover (e.g. a clobbered retry target) would make
            # the rename fail; the staged copy is the one the manifest
            # certifies.
            shutil.rmtree(final_dir)
        os.replace(staging_dir, final_dir)
        _fsync_dir(os.path.dirname(final_dir) or ".")
        self._event(
            "checkpoint_verify",
            seconds=time.perf_counter() - t0,
            dir=final_dir,
            phase="commit",
        )

    # -- verified load resolution -----------------------------------------

    def verify_before_load(self, input_dir: str) -> None:
        """Pre-restore guard for EXPLICIT checkpoint paths (the automatic
        resolver already verified its pick — that one is skipped here, not
        re-hashed). A torn explicit dir raises before any state is touched;
        a manifest-less legacy dir passes with a one-time warning."""
        if input_dir == getattr(self, "_last_verified_dir", None):
            return
        t0 = time.perf_counter()
        ok, reason = verify_checkpoint(
            input_dir, check_hashes=self.handler.checksum == "sha256"
        )
        self._event(
            "checkpoint_verify",
            seconds=time.perf_counter() - t0,
            dir=input_dir,
            ok=ok,
            reason=reason,
            phase="load",
        )
        if ok:
            return
        if reason == "no-manifest":
            logger.warning_once(
                f"fault_tolerance: {input_dir} has no manifest (saved before "
                "fault tolerance was enabled) — restoring it unverified."
            )
            return
        self._event("checkpoint_torn_skipped", dir=input_dir, reason=reason)
        raise RuntimeError(
            f"Refusing to restore torn checkpoint {input_dir}: {reason}. "
            "Use load_state() with automatic_checkpoint_naming to fall back "
            "to the newest verified checkpoint, or pass verify_on_load=False "
            "to restore it anyway."
        )

    def _note_topology(self, path: str) -> None:
        """Log when the resolver's pick was written under a different topology
        than the live run, so an elastic resume is visible in the
        fault-tolerance log before the restore path decides what to do."""
        try:
            from .resharding import (
                describe_topology,
                read_plan_manifest,
                topology_matches,
            )

            manifest = read_plan_manifest(path)
            if not manifest:
                return
            state = self.accelerator.state
            n_devices = len(state.devices)
            pc = getattr(state, "parallelism_config", None)
            layout = pc.layout_dict() if pc is not None else None
            if topology_matches(manifest, n_devices, layout):
                return
            logger.info(
                "fault_tolerance: %s was saved on %s; this run is %s — the "
                "restore path reshards it (or raises, if elastic restore is "
                "off).",
                path,
                describe_topology(manifest.get("n_devices"), manifest.get("layout")),
                describe_topology(n_devices, layout),
            )
            self._event(
                "checkpoint_topology",
                dir=path,
                src_devices=manifest.get("n_devices"),
                dst_devices=n_devices,
            )
        except Exception:  # pragma: no cover - advisory only
            pass

    def resolve_verified(self, base: str, names_ascending: list[str]) -> str:
        """Newest name whose manifest verifies; torn ones are logged, counted
        and skipped. Legacy dirs without a manifest are accepted with a
        one-time warning (they predate verification and cannot be checked)."""
        check_hashes = self.handler.checksum == "sha256"
        for name in reversed(names_ascending):
            path = os.path.join(base, name)
            t0 = time.perf_counter()
            ok, reason = verify_checkpoint(path, check_hashes=check_hashes)
            self._event(
                "checkpoint_verify",
                seconds=time.perf_counter() - t0,
                dir=path,
                ok=ok,
                reason=reason,
                phase="load",
            )
            if ok:
                self._last_verified_dir = path
                self._note_topology(path)
                return name
            if reason == "no-manifest":
                logger.warning_once(
                    f"fault_tolerance: {path} has no manifest (saved before "
                    "fault tolerance was enabled) — restoring it unverified."
                )
                self._last_verified_dir = path
                return name
            logger.warning(
                "fault_tolerance: skipping torn checkpoint %s (%s) — falling "
                "back to the next older one.",
                path, reason,
            )
            self._event("checkpoint_torn_skipped", dir=path, reason=reason)
        raise FileNotFoundError(
            f"No verifiable checkpoint in {base}: every candidate "
            f"({', '.join(reversed(names_ascending))}) failed manifest "
            "verification."
        )

    # -- save retry / fallback ---------------------------------------------

    def run_save_with_retry(self, do_save: Callable[[str], str], target_dir: str) -> str:
        """Run ``do_save(target_dir)`` with jittered exponential backoff on
        failure, then once against the fallback directory (same basename)
        when configured. Raises :class:`CheckpointSaveError` after that."""
        h = self.handler
        delay = max(0.0, float(h.retry_backoff_s))
        last_err: Optional[Exception] = None
        save_tick = self._save_ticks
        self._save_ticks += 1
        for attempt in range(max(0, int(h.save_retries)) + 1):
            try:
                # Injected torn_write faults raise here, per (save, attempt),
                # and flow through the identical retry/backoff/fallback path
                # a real storage flake takes. The fallback attempt below is
                # left clean — its coverage target is the primary dir dying.
                self._chaos_save_attempt(save_tick, attempt)
                out = do_save(target_dir)
                self._note_preemption_save(out)
                return out
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # OSError / TensorStore / safetensors I/O
                last_err = e
                shutil.rmtree(staging_path(target_dir), ignore_errors=True)
                if attempt < h.save_retries:
                    self.save_retries_total += 1
                    sleep_s = delay * (0.5 + random.random())
                    logger.warning(
                        "fault_tolerance: checkpoint save to %s failed "
                        "(attempt %d/%d, %s: %s); retrying in %.2fs.",
                        target_dir, attempt + 1, h.save_retries,
                        type(e).__name__, e, sleep_s,
                    )
                    self._event(
                        "checkpoint_save_retry",
                        dir=target_dir,
                        attempt=attempt + 1,
                        error=f"{type(e).__name__}: {e}"[:500],
                    )
                    time.sleep(sleep_s)
                    delay = min(delay * 2 or h.retry_backoff_s, h.retry_backoff_max_s)
        if h.fallback_dir:
            fallback_target = os.path.join(
                h.fallback_dir, os.path.basename(os.path.normpath(target_dir))
            )
            logger.warning(
                "fault_tolerance: primary checkpoint dir exhausted retries "
                "(%s: %s); falling back to %s.",
                type(last_err).__name__, last_err, fallback_target,
            )
            self._event(
                "checkpoint_fallback_save",
                dir=fallback_target,
                error=f"{type(last_err).__name__}: {last_err}"[:500],
            )
            try:
                os.makedirs(h.fallback_dir, exist_ok=True)
                out = do_save(fallback_target)
                self._note_preemption_save(out)
                return out
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                shutil.rmtree(staging_path(fallback_target), ignore_errors=True)
                raise CheckpointSaveError(
                    f"checkpoint save failed in the primary dir ({last_err}) "
                    f"AND the fallback dir {h.fallback_dir} ({e})"
                ) from e
        raise CheckpointSaveError(
            f"checkpoint save to {target_dir} failed after "
            f"{h.save_retries + 1} attempt(s): {last_err}"
        ) from last_err

    def _note_preemption_save(self, out_dir: str) -> None:
        """A save completed while the preemption flag was up: that save IS
        the preemption save — record it so the resumed run's zero-lost-steps
        claim is auditable from the telemetry stream."""
        if self.preempted:
            logger.info(
                "fault_tolerance: preemption save complete (%s, signal %s) — "
                "exit with PREEMPTION_EXIT_CODE (%d) for a resumable restart.",
                out_dir, self.preemption_signal, PREEMPTION_EXIT_CODE,
                main_process_only=True,
            )
            self._event(
                "preemption_save", dir=out_dir, signal=self.preemption_signal
            )

    # -- preemption signals ------------------------------------------------

    def install_signal_handlers(self) -> None:
        """Install SIGTERM/SIGUSR1 → preemption-flag handlers. Called from
        ``prepare()`` on every rank (the launcher signals the whole local
        gang, so local flags agree; multi-host coherence goes through
        ``check_preemption``'s collective). Harmless off the main thread or
        when already installed."""
        if not self.handler.install_signal_handlers or self._installed:
            return
        for name in self.handler.preemption_signals:
            signum = getattr(signal, name, None)
            if signum is None:
                continue
            try:
                prev = signal.signal(signum, self._on_signal)
            except ValueError:
                # Not the main thread (e.g. a dataloader worker constructed
                # the Accelerator) — signals only deliver to the main thread
                # anyway, so there is nothing to install here.
                logger.warning_once(
                    "fault_tolerance: cannot install signal handlers outside "
                    "the main thread; preemption auto-save is disabled for "
                    "this process."
                )
                return
            self._installed[signum] = prev

    def _on_signal(self, signum, frame) -> None:
        # Async-signal context: only set flags; the training loop observes
        # them at the next should_checkpoint()/check_preemption() poll.
        self.preempted = True
        try:
            self.preemption_signal = signal.Signals(signum).name
        except ValueError:
            self.preemption_signal = str(signum)

    def uninstall_signal_handlers(self) -> None:
        for signum, prev in self._installed.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._installed.clear()

    def clear_preemption(self) -> None:
        self.preempted = False
        self.preemption_signal = None

    @property
    def exit_code(self) -> int:
        return PREEMPTION_EXIT_CODE

    # -- divergence sentinel hook -----------------------------------------

    def observe_step(self, metrics, slot: int = 0):
        """Called by the prepared step wrapper after every step. Returns a
        replacement TrainState when a rollback restored one, else ``None``.
        Chaos draws and watchdog notes run first — they are live even with
        the sentinel off."""
        tick = self._step_ticks
        self._step_ticks += 1
        poison = False
        if self.chaos is not None:
            poison = self._chaos_train_step(tick)
        if self.watchdog is not None:
            self.watchdog.note_step(tick)  # may raise TrainingStalledError
            self.watchdog.maybe_heartbeat(tick)
        if self.sdc is not None:
            # Cross-replica integrity vote (lagged, collective on vote
            # ticks). Runs regardless of the divergence-sentinel policy.
            verdict = self.sdc.observe(
                metrics if isinstance(metrics, dict) else None, tick, slot)
            if verdict == "repair":
                return self._sdc_repair(slot)
        if self.handler.sentinel == "off":
            return None
        pending, self._pending_metrics = self._pending_metrics, None
        if isinstance(metrics, dict):
            if poison:
                self._pending_metrics = (float("nan"), float("nan"), slot)
            else:
                self._pending_metrics = (metrics.get("loss"), metrics.get("grad_norm"), slot)
        if pending is None:
            return None
        loss_arr, gnorm_arr, p_slot = pending
        try:
            loss = float(np.asarray(loss_arr)) if loss_arr is not None else None
            gnorm = float(np.asarray(gnorm_arr)) if gnorm_arr is not None else None
        except Exception:  # an undigestable metric must never kill training
            return None
        verdict, reason = self.sentinel.observe(loss, gnorm)
        if verdict == "ok":
            return None
        if verdict == "warn":
            return None  # below the streak window — keep counting quietly
        return self._trip(reason, p_slot)

    def _trip(self, reason: str, slot: int):
        policy = self.handler.sentinel
        step = self.accelerator.step
        if policy == "warn":
            if not self.sentinel.episode_warned:
                self.sentinel.episode_warned = True
                logger.warning(
                    "fault_tolerance: divergence detected (%s; %d consecutive "
                    "bad steps at step ~%d). Policy is 'warn' — training "
                    "continues; consider sentinel='rollback'.",
                    reason, self.sentinel.streak, step,
                )
                self._event(
                    "divergence", step=step, reason=reason, policy="warn",
                    streak=self.sentinel.streak,
                )
            self.sentinel.streak = 0  # re-arm: warn once per window, not per step
            return None
        if policy == "halt":
            self._event(
                "divergence", step=step, reason=reason, policy="halt",
                streak=self.sentinel.streak,
            )
            raise DivergenceError(
                f"training diverged ({reason}; {self.sentinel.streak} "
                f"consecutive bad steps) — policy 'halt'. Restore a "
                "checkpoint with load_state() or rerun with "
                "sentinel='rollback'."
            )
        # policy == "rollback"
        if self.rollbacks_done >= self.handler.max_rollbacks:
            # DivergenceError.exit_code is POISONED_CHECKPOINT_EXIT_CODE: a
            # supervised script exiting with it tells the launch supervisor
            # NOT to relaunch — the checkpoint reproduces the divergence.
            raise DivergenceError(
                f"training diverged again ({reason}) after "
                f"{self.rollbacks_done} rollback(s) — max_rollbacks "
                f"({self.handler.max_rollbacks}) exhausted; the divergence is "
                "reproducible from the checkpoint (bad data shard or LR "
                "schedule?), not transient."
            )
        try:
            restored = self.accelerator.load_state()
        except FileNotFoundError as e:
            raise DivergenceError(
                f"training diverged ({reason}) and rollback found no "
                f"verified checkpoint to restore: {e}"
            ) from e
        self.rollbacks_done += 1
        self.sentinel.reset()
        self._pending_metrics = None
        new_state = self.accelerator._train_states[slot]
        restored_step = int(np.asarray(new_state.step)) if new_state is not None else -1
        logger.warning(
            "fault_tolerance: divergence (%s) — rolled back to %s (step %d); "
            "%d rollback(s) remaining.",
            reason, restored, restored_step,
            self.handler.max_rollbacks - self.rollbacks_done,
        )
        self._event(
            "rollback", step=step, reason=reason, dir=restored,
            restored_step=restored_step, rollbacks=self.rollbacks_done,
        )
        return new_state

    def _sdc_repair(self, slot: int):
        """A vote mismatch the probe classified as *transient*: repair in
        place and return the replacement TrainState. ``repair="broadcast"``
        re-syncs params from a majority replica (falling back to rollback
        when the vote had no majority to trust); ``"rollback"`` restores the
        newest verified checkpoint — the replay is bit-equal to fault-free
        because the corruption lived only in one replica's observed digest
        stream, never in the verified bytes on disk."""
        step = self.accelerator.step
        mode = self.sdc.config.repair
        new_state = None
        if mode == "broadcast":
            try:
                new_state = self.sdc.broadcast_params(slot)
            except Exception as e:
                logger.warning(
                    "fault_tolerance: sdc broadcast repair failed (%s) — "
                    "falling back to rollback.", e)
        restored = None
        if new_state is None:
            mode = "rollback"
            try:
                restored = self.accelerator.load_state()
            except FileNotFoundError as e:
                from .sdc import SDCError

                raise SDCError(
                    "transient silent corruption detected but the rollback "
                    f"repair found no verified checkpoint to restore: {e}"
                ) from e
            new_state = self.accelerator._train_states[slot]
        self.sdc.note_repair(mode)
        # Both repair paths invalidate the in-flight lagged metrics: the
        # pending digest/loss describe a step the repair just rewound.
        self._pending_metrics = None
        self.sentinel.reset()
        restored_step = (int(np.asarray(new_state.step))
                         if new_state is not None else -1)
        self._event(
            "sdc_repair", step=step, mode=mode, dir=restored,
            restored_step=restored_step, repairs=self.sdc.repairs_done,
        )
        return new_state

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self.uninstall_signal_handlers()
        self._pending_metrics = None
