"""Big-model inference benchmark: load time + per-token decode latency on the
real chip — the shape of the reference's headline table
(/root/reference/benchmarks/big_model_inference/README.md:25-33: GPT-J-6B
fp16 loads in 8.7 s and generates at 0.05 s/token on 2x Titan RTX, etc.).

Three rows, one JSON line each:

- ``load``: sharded-safetensors checkpoint -> chip via
  load_checkpoint_and_dispatch (meta init, stream shards into placements) —
  the reference's "load time" column.
- ``resident``: KV-cache generate() with all params HBM-resident — prefill
  latency + steady-state per-token time.
- ``streamed``: params held in host RAM, layer-streamed forward
  (dispatch_model with transformer blocks on "cpu") — the reference's
  CPU-offload rows, where per-token cost is dominated by weight streaming.
- ``--serving`` adds two rows: mixed-length Poisson arrivals through the
  continuous-batching :class:`ServingEngine` vs the SAME request set through
  gang-scheduled static-batch ``generate()`` — aggregate tokens/s, p50/p95
  TTFT (static TTFT = batch completion minus arrival: requests wait for
  the gang), and recompile/executable counts per phase.
- ``--disagg`` (implies ``--serving``) adds a ``serving_disagg`` row: the
  same Poisson trace through the two-mesh
  :class:`~accelerate_tpu.disagg.DisaggServingEngine` (planner-sized
  prefill/decode slices, streamed KV-page handoff) with the telemetry
  ``disagg`` block embedded in the row.
- ``--chaos`` (implies ``--serving``) adds a ``serving_chaos`` row: the
  same trace under a seed-driven :class:`~accelerate_tpu.chaos.FaultInjector`
  (rate-driven handoff transfer errors + one dead lane when disaggregated,
  a poisoned KV page always) with the ``serving.faults`` telemetry block —
  status counts, retries, quarantines, injected-fault log size — embedded
  in the row, so robustness overhead shows up in the perf trajectory next
  to the fault-free rows.
- ``--publish`` (implies ``--serving``) adds a ``serving_publish`` row: a
  committed, manifest-verified checkpoint hot-swapped into the live engine
  mid-trace by the :class:`~accelerate_tpu.publish.WeightPublisher` —
  swap latency, BandwidthTable-priced redistribution bytes, the canary
  window (routed counts + decision), and the faults block, with the
  zero-recompile swap evidenced by the executable census.
- ``--journal`` (implies ``--serving``) adds one ``serving_journal`` row
  per write-ahead-journal fsync policy (``every_record`` / ``every_tick`` /
  ``os``) — the SAME trace with crash-durable request journaling on,
  priced as tokens/s overhead vs the journal-off ``serving`` row — plus a
  ``journal_recovery`` row: a journaled engine is abandoned mid-trace (a
  simulated crash) and a fresh engine's measured ``recover()`` wall time,
  recovered counts, and drained completions ride in the row.
- ``--sdc`` (implies ``--serving``) adds a ``serving_sdc`` row: the same
  trace with a :class:`~accelerate_tpu.sdc.DecodeCanary` re-running a
  known prompt through the live slot machinery every ``--sdc-every``
  ticks — the silent-data-corruption detection tax priced as tokens/s
  overhead vs the canary-off ``serving`` row (target < 1%), with the
  ``sdc`` stats block (probes / mismatches / quarantines) in the row.
- ``--trace diurnal`` swaps the flat Poisson arrivals for the seeded
  diurnal generator (:func:`accelerate_tpu.autoscale.make_diurnal_trace`:
  low / 10x-high / low plateaus with a shifting prompt:decode mix) — ONE
  request set shared by every serving row above, so static, continuous,
  disagg, chaos, and publish are priced on identical load.
- ``--autoscale`` (implies ``--serving`` and ``--trace diurnal``) adds a
  ``serving_autoscale`` row: the trace through a disagg engine that starts
  on HALF the mesh with an :class:`~accelerate_tpu.autoscale.
  AutoscaleController` closing the loop — resize count and decision
  counters, a per-plateau SLO block (p95 TTFT on the high vs low
  plateaus), and the executable census proving resizes did not recompile
  the steady state.

    python benchmarks/generate_bench.py [--params-b 1] [--new-tokens 64]
                                        [--serving] [--disagg] [--chaos]
                                        [--publish] [--autoscale]
                                        [--trace poisson|diurnal] [--qps 8]
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(params_b: float):
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig

    if params_b >= 1.0:
        # The bench.py 1.06B config.
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=18, num_attention_heads=16, num_key_value_heads=16,
            max_position_embeddings=2048, dtype=jnp.bfloat16,
        )
    elif params_b < 0.01:
        # CPU-verifiable tier (CI smoke of the bench plumbing itself).
        cfg = LlamaConfig.tiny(dtype=jnp.float32, max_position_embeddings=2048)
    else:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=4096,
            num_hidden_layers=16, num_attention_heads=8, num_key_value_heads=8,
            max_position_embeddings=2048, dtype=jnp.bfloat16,
        )
    return cfg


def _tracing_block(tr):
    """The bench-row ``tracing`` block: span counts by kind plus the
    critical-path breakdown of the p95-TTFT request — the one the SLO
    report would name when asked "why is tail latency what it is?"."""
    stats = tr.stats()
    block = {"spans": stats["spans"], "by_kind": stats["by_kind"]}
    pairs = []
    for rid in tr.request_ids():
        rep = tr.explain(rid)
        if rep["terms"] is not None:
            pairs.append((rep["ttft_s"], rid))
    if pairs:
        pairs.sort()
        p95 = float(np.percentile([p[0] for p in pairs], 95))
        ttft, rid = next((p for p in pairs if p[0] >= p95), pairs[-1])
        rep = tr.explain(rid)
        block["p95_request"] = {
            "request_id": rid, "ttft_s": round(ttft, 6),
            "dominant": rep["dominant"],
            "terms": {k: round(v, 6) for k, v in rep["terms"].items()},
        }
    return block


def _profile_block(prof):
    """The bench-row ``profile`` block (profiler.py DeviceTimeProfiler):
    per-tick device-time attribution means — where each engine tick's wall
    went (admit / prefill / decode / host fetch / bookkeeping residual).
    ``overlap_ratio_mean`` and ``bandwidth_residuals`` only fill in when a
    training plan priced the profiler; serving-only rows carry them empty
    rather than invented."""
    s = prof.summary()
    block = {k: s.get(k) for k in ("ticks", "overlap_ratio_mean",
                                   "bandwidth_residuals")}
    terms = s.get("tick_terms_mean_s") or {}
    block["tick_terms_mean_s"] = {k: round(v, 6) for k, v in terms.items()}
    return block


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--params-b", type=float, default=1.0)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--streamed-tokens", type=int, default=4)
    ap.add_argument("--int8", action="store_true",
                    help="add a resident_int8 row (DecodeQuant weight-only decode)")
    ap.add_argument("--serving", action="store_true",
                    help="add serving rows (continuous batching vs static gang)")
    ap.add_argument("--disagg", action="store_true",
                    help="add a disaggregated-serving row (two-mesh router on "
                         "the same Poisson trace; implies --serving)")
    ap.add_argument("--lanes", type=int, default=4,
                    help="prefill lanes for the --disagg row")
    ap.add_argument("--chaos", action="store_true",
                    help="add a serving_chaos row (same trace under a "
                         "deterministic FaultInjector; implies --serving)")
    ap.add_argument("--chaos-seed", type=int, default=7)
    ap.add_argument("--publish", action="store_true",
                    help="add a serving_publish row (hot-swap a committed "
                         "checkpoint into the live engine mid-trace through "
                         "a canary window; implies --serving)")
    ap.add_argument("--canary-fraction", type=float, default=0.25)
    ap.add_argument("--journal", action="store_true",
                    help="add serving_journal rows (WAL overhead per fsync "
                         "policy vs journal-off) and a journal_recovery row "
                         "(measured recover() time on a fresh engine after "
                         "a simulated crash; implies --serving)")
    ap.add_argument("--sdc", action="store_true",
                    help="add a serving_sdc row (same trace with a "
                         "DecodeCanary probing every few ticks; prices the "
                         "canary overhead against the canary-off serving "
                         "row — target < 1%% tokens/s; implies --serving)")
    ap.add_argument("--sdc-every", type=int, default=8,
                    help="canary probe cadence in engine ticks for --sdc")
    ap.add_argument("--fleet", action="store_true",
                    help="add a serving_fleet row (the request set through a "
                         "two-cell FleetRouter with a seeded cell_crash "
                         "killing cell 0 mid-trace: per-cell tokens/s, "
                         "spillover rate, measured drain time, executable "
                         "census per cell; implies --serving)")
    ap.add_argument("--autoscale", action="store_true",
                    help="add a serving_autoscale row (diurnal trace through "
                         "a half-mesh disagg engine with an "
                         "AutoscaleController closing the loop; implies "
                         "--serving and --trace diurnal)")
    ap.add_argument("--speculative", action="store_true",
                    help="add serving_speculative rows (n-gram self-draft "
                         "decode, acceptance-friendly vs adversarial "
                         "traffic, each priced against its non-speculative "
                         "baseline; implies --serving)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per slot per tick for --speculative")
    ap.add_argument("--spec-ngram", type=int, default=64,
                    help="n-gram history window for --speculative")
    ap.add_argument("--kv-dtype", choices=("model", "int8"), default="model",
                    help="KV-page dtype for the --disagg row; int8 "
                         "quantizes pages (QuantPages) and reports the "
                         "handoff bytes saved")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--qps", type=float, default=8.0,
                    help="Poisson arrival rate for the serving rows (the "
                         "diurnal trace's low-plateau rate)")
    ap.add_argument("--tracing", action="store_true",
                    help="attach a TraceRecorder to every serving row and "
                         "embed a tracing block (span counts + critical-path "
                         "breakdown of the p95-TTFT request)")
    ap.add_argument("--trace-out", type=str, default=None,
                    help="dump the last traced serving row as Perfetto-"
                         "loadable Chrome trace JSON (implies --tracing)")
    ap.add_argument("--trace", choices=("poisson", "diurnal"),
                    default="poisson",
                    help="arrival process shared by every serving row")
    ap.add_argument("--trace-seed", type=int, default=1)
    args = ap.parse_args()
    if args.autoscale:
        args.trace = "diurnal"
    if args.trace_out:
        args.tracing = True
    if args.disagg or args.chaos or args.publish or args.autoscale \
            or args.journal or args.sdc or args.fleet or args.speculative:
        args.serving = True

    # Streaming-evidence rule (round-3 postmortem, same as bench.py): emit a
    # parseable row the moment anything is known, flushed — a driver timeout
    # must never leave an empty tail.
    print(json.dumps({"row": "start", "params_b": args.params_b}), flush=True)

    import jax
    import jax.numpy as jnp

    from accelerate_tpu.utils.environment import honor_jax_platforms_env

    honor_jax_platforms_env()

    from accelerate_tpu import Model, dispatch_model, load_checkpoint_and_dispatch
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaForCausalLM
    from accelerate_tpu.utils.other import flatten_state_dict, save_sharded_safetensors

    cfg = build(args.params_b)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=(1, args.prompt_len), dtype=np.int32)

    # Build once on host, export a sharded checkpoint to load from.
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        model = Model.from_flax(module, jax.random.key(0), prompt)
        host_params = jax.tree.map(lambda x: np.asarray(x), model.params)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(host_params))
    ckpt = tempfile.mkdtemp(prefix="gen_bench_ckpt_")
    save_sharded_safetensors(
        {k: np.asarray(v) for k, v in flatten_state_dict(host_params).items()},
        ckpt, max_shard_size=2 * 1024**3,
    )

    device_kind = getattr(jax.devices()[0], "device_kind", jax.devices()[0].platform)

    # --- Row 1: load time (disk -> chip, meta init + shard streaming) ------
    t0 = time.perf_counter()
    resident = load_checkpoint_and_dispatch(module, ckpt, prompt, device_map=None)
    # Materialize: a forward forces every param onto the chip.
    np.asarray(resident(prompt[:, :8]))
    load_s = time.perf_counter() - t0
    print(json.dumps({
        "row": "load", "seconds": round(load_s, 2),
        "params_b": round(n_params / 1e9, 3), "device_kind": device_kind,
    }), flush=True)

    # --- Row 2: resident KV-cache decode ----------------------------------
    # device_map=None placed every param on chip 0; reuse that tree directly.
    res_model = Model(module=module, params=resident.params)

    t0 = time.perf_counter()
    out = generate(res_model, prompt, max_new_tokens=args.new_tokens)
    out.block_until_ready()
    np.asarray(out)
    first_s = time.perf_counter() - t0  # includes compile
    t0 = time.perf_counter()
    out = generate(res_model, prompt, max_new_tokens=args.new_tokens)
    np.asarray(out)
    warm_s = time.perf_counter() - t0
    per_token = warm_s / args.new_tokens
    print(json.dumps({
        "row": "resident", "s_per_token": round(per_token, 4),
        "tokens_per_s": round(1.0 / per_token, 1),
        "warm_generate_s": round(warm_s, 3),
        "first_call_s": round(first_s, 2),
        "new_tokens": args.new_tokens,
    }), flush=True)

    # --- Optional row: int8 weight-only resident decode --------------------
    if args.int8:
        from accelerate_tpu.utils.quantization import (
            quantize_model_for_decode, quantized_nbytes,
        )
        from accelerate_tpu.generation import clear_generation_cache

        qm = quantize_model_for_decode(res_model)
        clear_generation_cache()
        np.asarray(generate(qm, prompt, max_new_tokens=args.new_tokens))  # compile
        t0 = time.perf_counter()
        out = generate(qm, prompt, max_new_tokens=args.new_tokens)
        np.asarray(out)
        warm_q = time.perf_counter() - t0
        print(json.dumps({
            "row": "resident_int8", "s_per_token": round(warm_q / args.new_tokens, 4),
            "tokens_per_s": round(args.new_tokens / warm_q, 1),
            "weight_bytes": int(quantized_nbytes(qm.params)),
            "weight_bytes_bf16": int(quantized_nbytes(res_model.params)),
            "new_tokens": args.new_tokens,
        }), flush=True)
        qm = None  # free the int8 copy + its executables before the
        clear_generation_cache()  # streamed row's per-layer buffers

    # --- Optional rows: continuous batching vs static gang -----------------
    if args.serving:
        from accelerate_tpu import ServingConfig, ServingEngine
        from accelerate_tpu import generation as G
        from accelerate_tpu.generation import clear_generation_cache

        def _recorder():
            if not args.tracing:
                return None
            from accelerate_tpu import TraceConfig, TraceRecorder

            return TraceRecorder(TraceConfig())

        export_tr = None  # the last traced row's recorder (--trace-out)

        srng = np.random.default_rng(1)
        n, slots = args.requests, args.slots
        phases = None
        if args.trace == "diurnal":
            # One seeded diurnal trace shared by EVERY serving row below:
            # low / high / low plateaus at a 10x rate swing with the
            # prompt:decode mix shifting against it (autoscale.py).
            from accelerate_tpu.autoscale import make_diurnal_trace

            dtrace = make_diurnal_trace(n, seed=args.trace_seed,
                                        base_rate=args.qps,
                                        vocab_size=cfg.vocab_size)
            reqs = dtrace["prompts"]
            lengths = np.asarray(dtrace["lengths"])
            budgets = np.asarray(dtrace["budgets"], dtype=int)
            arrivals = np.asarray(dtrace["arrivals"])
            phases = np.asarray(dtrace["phases"])
        else:
            lengths = srng.integers(4, max(9, args.prompt_len), n)
            budgets = np.where(
                srng.random(n) < 0.5,
                srng.integers(4, 12, n),
                srng.integers(max(2, args.new_tokens // 2),
                              args.new_tokens + 1, n),
            ).astype(int)
            reqs = [srng.integers(1, cfg.vocab_size, (int(L),),
                                  dtype=np.int32) for L in lengths]
            arrivals = np.cumsum(srng.exponential(1.0 / args.qps, n))
        useful = int(budgets.sum())

        # Static gang: batches of `slots` in arrival order, left-padded to
        # the batch max prompt, every row decoding the batch max budget. A
        # request's TTFT is its batch's completion minus its arrival — the
        # gang cannot release anything early.
        clear_generation_cache()
        t0 = time.perf_counter()
        batch_done = {}
        for i0 in range(0, n, slots):
            batch = list(range(i0, min(i0 + slots, n)))
            smax = max(len(reqs[i]) for i in batch)
            bmax = int(max(budgets[i] for i in batch))
            ids = np.zeros((len(batch), smax), np.int32)
            mask = np.zeros((len(batch), smax), np.int32)
            for r, i in enumerate(batch):
                p = reqs[i]
                ids[r, smax - len(p):] = p
                mask[r, smax - len(p):] = 1
            np.asarray(generate(res_model, ids, max_new_tokens=bmax,
                                attention_mask=mask))
            done = time.perf_counter() - t0
            for i in batch:
                batch_done[i] = done
        static_s = time.perf_counter() - t0
        ttft_static = np.asarray(
            [max(0.0, batch_done[i] - arrivals[i]) for i in range(n)]
        )
        static_execs = sum(
            int(fn._cache_size()) for fn in G._GEN_LOOP_CACHE.values()
            if callable(getattr(fn, "_cache_size", None))
        )
        print(json.dumps({
            "row": "serving_static", "seconds": round(static_s, 3),
            "useful_tokens": useful,
            "tokens_per_s": round(useful / static_s, 2),
            "ttft_p50_s": round(float(np.percentile(ttft_static, 50)), 4),
            "ttft_p95_s": round(float(np.percentile(ttft_static, 95)), 4),
            "compiled_executables": static_execs,
        }), flush=True)

        # Continuous batching: the SAME Poisson trace replayed open-loop
        # (arrival times fixed up front — offered load does not adapt to the
        # engine's drain rate). Warmup first so compiles stay out of TTFT.
        from accelerate_tpu.serving import replay_trace

        t_cap = int(max(lengths[i] + budgets[i] for i in range(n))) + 8
        scfg = ServingConfig(n_slots=slots, max_len=t_cap,
                             max_prefill_chunk=max(16, args.prompt_len))
        tr_serve = _recorder()
        # Standalone device-time profiler: lagged per-tick attribution
        # (host perf_counter sections, zero extra device syncs) rides the
        # row so WHERE each tick's wall went travels with the latencies.
        from accelerate_tpu.profiler import DeviceTimeProfiler

        prof_serve = DeviceTimeProfiler()
        engine = ServingEngine(res_model, scfg, tracing=tr_serve,
                               profiler=prof_serve)
        engine.warmup()
        _, serve_s = replay_trace(engine, reqs, arrivals=list(arrivals),
                                  max_new_tokens=[int(b) for b in budgets])
        st = engine.stats()
        row = {
            "row": "serving", "seconds": round(serve_s, 3),
            "useful_tokens": st["tokens_out"],
            "tokens_per_s": st["tokens_per_s"],
            "ttft_p50_s": round(st["ttft_p50_s"], 4),
            "ttft_p95_s": round(st["ttft_p95_s"], 4),
            "tpot_mean_s": round(st["tpot_mean_s"], 4),
            "mean_occupancy": st["mean_occupancy"],
            "decode_executables": st["decode_executables"],
            "prefill_executables": st["prefill_executables"],
            "steady_recompiles": st["steady_recompiles"],
            "faults": st["faults"],
            "speculation": st["speculation"],
        }
        prof_serve.flush()  # finalize the lagged last tick
        row["profile"] = _profile_block(prof_serve)
        if tr_serve is not None:
            row["tracing"] = _tracing_block(tr_serve)
            export_tr = tr_serve
        print(json.dumps(row), flush=True)

        # Speculative rows: raw decode throughput with the n-gram
        # self-draft on, against the non-speculative baseline on the SAME
        # mesh, model, and request set (everything submitted at t=0 so the
        # arrival process never caps the measured decode rate). Two traffic
        # classes: acceptance-friendly uses a Markov-collapsed model
        # variant (attention output projections zeroed, so continuations
        # settle into cycles — the repetitive-output regime where n-gram
        # drafts shine: boilerplate, JSON, copy-heavy completions);
        # adversarial uses the raw model, whose continuations stay chaotic
        # and acceptance sits near the floor — the honest worst case.
        if args.speculative:
            spec_budget = int(args.new_tokens)
            spec_cap = int(max(len(r) for r in reqs)) + spec_budget + 8

            def _collapsed_params(tree):
                new = jax.tree.map(lambda x: x, tree)
                mp = new["model"] if "model" in new else new
                blk = mp["layers"]["block"]
                blk["self_attn"]["o_proj"]["kernel"] = jnp.zeros_like(
                    blk["self_attn"]["o_proj"]["kernel"])
                return new

            friendly_model = Model(module=module,
                                   params=_collapsed_params(res_model.params))

            def _spec_run(mdl, k):
                ecfg = ServingConfig(
                    n_slots=slots, max_len=spec_cap,
                    max_prefill_chunk=max(16, args.prompt_len),
                    speculate_k=k, speculate_ngram=args.spec_ngram)
                eng = ServingEngine(mdl, ecfg)
                eng.warmup()
                t0 = time.perf_counter()
                eng.run([r.copy() for r in reqs],
                        max_new_tokens=spec_budget)
                wall = time.perf_counter() - t0
                est = eng.stats()
                eng.close()
                return est, wall

            for traffic, mdl in (("acceptance_friendly", friendly_model),
                                 ("adversarial", res_model)):
                clear_generation_cache()
                bst, b_wall = _spec_run(mdl, 0)
                sst, s_wall = _spec_run(mdl, args.spec_k)
                b_tps = bst["tokens_out"] / b_wall
                s_tps = sst["tokens_out"] / s_wall
                sp = sst["speculation"]
                print(json.dumps({
                    "row": "serving_speculative", "traffic": traffic,
                    "k": args.spec_k, "ngram": args.spec_ngram,
                    "tokens_per_s": round(s_tps, 2),
                    "tokens_per_s_baseline": round(b_tps, 2),
                    "speedup": round(s_tps / b_tps, 3) if b_tps else None,
                    "acceptance_rate": sp["acceptance_rate"],
                    "tokens_per_tick": sp["tokens_per_tick"],
                    "tokens_per_tick_baseline": (
                        round(bst["tokens_out"] / bst["decode_steps"], 6)
                        if bst["decode_steps"] else None),
                    "decode_steps": sst["decode_steps"],
                    "decode_steps_baseline": bst["decode_steps"],
                    "decode_executables": sst["decode_executables"],
                    "steady_recompiles": sst["steady_recompiles"],
                    "faults": sst["faults"],
                    "speculation": sp,
                }), flush=True)
            friendly_model = None
            clear_generation_cache()

        # Journal rows: the same trace with the crash-durable write-ahead
        # request journal on, one row per fsync policy — the durability tax
        # priced against the journal-off `serving` row above. every_record
        # pays an fsync per append, every_tick (the default) one per engine
        # tick, os only flushes to the page cache.
        if args.journal:
            from accelerate_tpu.journal import JOURNAL_FSYNC_POLICIES

            base_tps = st["tokens_per_s"]
            jroot = tempfile.mkdtemp(prefix="gen_bench_journal_")
            for pol in JOURNAL_FSYNC_POLICIES:
                jcfg = ServingConfig(
                    n_slots=slots, max_len=t_cap,
                    max_prefill_chunk=max(16, args.prompt_len),
                    journal_dir=os.path.join(jroot, pol), journal_fsync=pol)
                jengine = ServingEngine(res_model, jcfg)
                jengine.warmup()
                _, jour_s = replay_trace(
                    jengine, reqs, arrivals=list(arrivals),
                    max_new_tokens=[int(b) for b in budgets])
                jst = jengine.stats()
                jj = jst["journal"]
                print(json.dumps({
                    "row": "serving_journal", "fsync": pol,
                    "seconds": round(jour_s, 3),
                    "tokens_per_s": jst["tokens_per_s"],
                    "tokens_per_s_journal_off": base_tps,
                    "overhead_pct": (round(100.0 * (base_tps - jst[
                        "tokens_per_s"]) / base_tps, 2) if base_tps else None),
                    "appends": jj["appends"], "syncs": jj["syncs"],
                    "rotations": jj["rotations"],
                    "bytes_written": jj["bytes_written"],
                    "decode_executables": jst["decode_executables"],
                    "steady_recompiles": jst["steady_recompiles"],
                }), flush=True)
                jengine.close()

            # Measured recovery: feed the whole request set to a journaled
            # engine, abandon it after a handful of ticks WITHOUT close()
            # (a simulated crash — the WAL is the only survivor), then time
            # a fresh engine's recover() over the same directory and drain
            # the replayed queue to completion.
            rcfg = ServingConfig(
                n_slots=slots, max_len=t_cap,
                max_prefill_chunk=max(16, args.prompt_len),
                journal_dir=os.path.join(jroot, "recover"))
            crash_engine = ServingEngine(res_model, rcfg)
            crash_engine.warmup()
            for i in range(n):
                crash_engine.submit(reqs[i], max_new_tokens=int(budgets[i]),
                                    client_request_id=f"bench-{i}")
            for _ in range(16):
                if crash_engine.pending:
                    crash_engine.tick()
            crash_engine.poll()
            del crash_engine  # simulated crash: no close(), no flush
            fresh = ServingEngine(res_model, rcfg)
            fresh.warmup()
            t0 = time.perf_counter()
            rec = fresh.recover()
            recover_wall_s = time.perf_counter() - t0
            drained = 0
            while fresh.pending:
                fresh.tick()
                drained += sum(1 for r in fresh.poll()
                               if r["status"] == "ok")
            print(json.dumps({
                "row": "journal_recovery",
                "recover_s": round(recover_wall_s, 4),
                "recovered_inflight": rec["recovered_inflight"],
                "recovered_terminal": rec["recovered_terminal"],
                "records_scanned": rec["records"],
                "segments": rec["segments"],
                "torn_tails": rec["torn_tails"],
                "corrupt_skipped": rec["corrupt_skipped"],
                "drained_ok": drained,
                "requests": n,
            }), flush=True)
            fresh.close()

        # SDC-canary row: the same trace with a DecodeCanary re-running a
        # known prompt through the live slot machinery every --sdc-every
        # ticks — the silent-data-corruption detection tax priced against
        # the canary-off `serving` row above (target: < 1% tokens/s). The
        # probe rides the compiled decode ladder and is suppressed from
        # poll()/journal/stats, so the only cost is its slot occupancy.
        if args.sdc:
            from accelerate_tpu.sdc import DecodeCanary

            dcfg = ServingConfig(n_slots=slots, max_len=t_cap,
                                 max_prefill_chunk=max(16, args.prompt_len))
            dengine_sdc = ServingEngine(res_model, dcfg)
            dengine_sdc.warmup()
            canary = DecodeCanary(dengine_sdc, every=args.sdc_every)
            canary.warmup()
            dengine_sdc.reset_metrics()  # warmup probe out of the measurement
            _, sdc_s = replay_trace(dengine_sdc, reqs,
                                    arrivals=list(arrivals),
                                    max_new_tokens=[int(b) for b in budgets])
            dst_sdc = dengine_sdc.stats()
            base_tps = st["tokens_per_s"]
            print(json.dumps({
                "row": "serving_sdc", "seconds": round(sdc_s, 3),
                "canary_every": args.sdc_every,
                "useful_tokens": dst_sdc["tokens_out"],
                "tokens_per_s": dst_sdc["tokens_per_s"],
                "tokens_per_s_canary_off": base_tps,
                "overhead_pct": (round(100.0 * (base_tps - dst_sdc[
                    "tokens_per_s"]) / base_tps, 2) if base_tps else None),
                "ttft_p50_s": round(dst_sdc["ttft_p50_s"], 4),
                "ttft_p95_s": round(dst_sdc["ttft_p95_s"], 4),
                "steady_recompiles": dst_sdc["steady_recompiles"],
                "sdc": dst_sdc["sdc"],
            }), flush=True)

        # Fleet row: the same request set through a two-cell FleetRouter
        # (one journaled engine per cell) with a seeded cell_crash killing
        # cell 0 mid-trace — prices whole-cell failover: the router adopts
        # the dead cell's journal and drains it onto the survivor. Per-cell
        # tokens/s, spillover rate, the measured drain time, and the
        # executable census per surviving cell ride the row.
        if args.fleet:
            from accelerate_tpu import FaultInjector, FleetRouter

            froot = tempfile.mkdtemp(prefix="gen_bench_fleet_")
            fcells = {}
            for i in range(2):
                feng = ServingEngine(res_model, ServingConfig(
                    n_slots=slots, max_len=t_cap,
                    max_prefill_chunk=max(16, args.prompt_len),
                    journal_dir=os.path.join(froot, f"wal{i}")))
                feng.warmup()
                fcells[f"c{i}"] = feng
            crash_tick = max(2, n // 3)
            fchaos = FaultInjector(seed=args.chaos_seed, schedule=[
                {"point": "cell_crash", "kind": "crash",
                 "tick": crash_tick, "unit": 0}])
            frouter = FleetRouter(fcells, chaos=fchaos)
            fok = 0
            t0 = time.perf_counter()
            for i in range(n):  # tick-aligned arrivals: one per router tick
                frouter.submit(reqs[i], max_new_tokens=int(budgets[i]),
                               client_request_id=f"fleet-bench-{i}",
                               session_id=f"sess-{i}")
                frouter.tick()
                fok += sum(1 for r in frouter.poll()
                           if r["status"] == "ok")
            while frouter.pending:
                frouter.tick()
                fok += sum(1 for r in frouter.poll()
                           if r["status"] == "ok")
            fleet_s = time.perf_counter() - t0
            fs = frouter.stats()
            fper = {}
            for name, block in fs["per_cell"].items():
                cell = frouter._cells[name]
                fper[name] = {
                    "state": block["state"],
                    "tokens_per_s": (cell.engine.stats()["tokens_per_s"]
                                     if not cell.dead else None),
                    "requests_completed": block["requests_completed"],
                    "decode_executables": block["decode_executables"],
                    "steady_recompiles": block["steady_recompiles"],
                }
            print(json.dumps({
                "row": "serving_fleet", "seconds": round(fleet_s, 3),
                "cells": fs["cells"], "dead": fs["dead"],
                "crash_tick": crash_tick, "requests": n, "ok": fok,
                "spillover_rate": (round(
                    fs["routed_spilled"] / fs["submitted"], 4)
                    if fs["submitted"] else None),
                "shed": fs["shed"],
                "drain_s": fs["drain_last_s"],
                "drained_cached": fs["drained_cached"],
                "drained_resubmitted": fs["drained_resubmitted"],
                "per_cell": fper,
            }), flush=True)
            frouter.close()

        # Disaggregated row: the same trace through the two-mesh router —
        # planner-sized prefill/decode slices, streamed KV-page handoff. The
        # telemetry `disagg` block rides inside the row (slice plan, handoff
        # bytes/latency, measured FLOP ratio).
        if args.disagg and len(jax.devices()) < 2:
            print(json.dumps({
                "row": "serving_disagg", "skipped": "needs >= 2 devices",
            }), flush=True)
        elif args.disagg:
            from accelerate_tpu import DisaggConfig, DisaggServingEngine

            tr_dis = _recorder()
            prof_dis = DeviceTimeProfiler()
            dis_cfg = scfg
            if args.kv_dtype == "int8":
                dis_cfg = ServingConfig(
                    n_slots=slots, max_len=t_cap,
                    max_prefill_chunk=max(16, args.prompt_len),
                    cache_dtype=jnp.int8)
            dengine = DisaggServingEngine(
                res_model, dis_cfg,
                disagg=DisaggConfig(n_prefill_lanes=args.lanes),
                tracing=tr_dis, profiler=prof_dis,
            )
            dengine.warmup()
            _, dis_s = replay_trace(dengine, reqs, arrivals=list(arrivals),
                                    max_new_tokens=[int(b) for b in budgets])
            dst = dengine.stats()
            row = {
                "row": "serving_disagg", "seconds": round(dis_s, 3),
                "useful_tokens": dst["tokens_out"],
                "tokens_per_s": dst["tokens_per_s"],
                "ttft_p50_s": round(dst["ttft_p50_s"], 4),
                "ttft_p95_s": round(dst["ttft_p95_s"], 4),
                "tpot_mean_s": round(dst["tpot_mean_s"], 4),
                "decode_executables": dst["decode_executables"],
                "steady_recompiles": dst["steady_recompiles"],
                "disagg": dst["disagg"],
            }
            if args.kv_dtype == "int8":
                # Byte accounting: what the SAME trace would have moved in
                # the model's own cache dtype, per the planner's dtype-aware
                # per-token pricing — the saved fraction is the honest
                # "4x fewer handoff bytes" number.
                from accelerate_tpu.planner import kv_bytes_per_token

                moved = int(dst["disagg"]["handoff_bytes"])
                per_q = kv_bytes_per_token(cfg, dtype=jnp.int8)
                per_f = kv_bytes_per_token(cfg)
                unq = int(round(moved * per_f / per_q)) if per_q else None
                row["kv_dtype"] = "int8"
                row["handoff_bytes"] = moved
                row["handoff_bytes_unquantized_est"] = unq
                row["handoff_bytes_saved_pct"] = (
                    round(100.0 * (unq - moved) / unq, 2) if unq else None)
            prof_dis.flush()  # finalize the lagged last tick
            row["profile"] = _profile_block(prof_dis)
            if tr_dis is not None:
                row["tracing"] = _tracing_block(tr_dis)
                export_tr = tr_dis
            print(json.dumps(row), flush=True)

        # Chaos row: the same trace under a deterministic FaultInjector —
        # the robustness overhead (retries, quarantines, degraded fallback)
        # priced against the fault-free rows above. Disaggregated when
        # --disagg ran (handoff faults + a dead lane); colocated otherwise
        # (a poisoned KV page through the decode sentinel).
        if args.chaos:
            from accelerate_tpu import FaultInjector

            use_disagg = args.disagg and len(jax.devices()) >= 2
            rates = {"handoff_device_put": {"transfer_error": 0.05}} \
                if use_disagg else {}
            schedule = [{"point": "decode_tick", "kind": "poison", "tick": 25}]
            if use_disagg:
                schedule.append({"point": "lane_health", "kind": "dead_lane",
                                 "unit": 0})
            chaos = FaultInjector(seed=args.chaos_seed, rates=rates,
                                  schedule=schedule)
            ccfg = ServingConfig(n_slots=slots, max_len=t_cap,
                                 max_prefill_chunk=max(16, args.prompt_len),
                                 max_retries=3,
                                 max_idle_ticks=max(100, 4 * t_cap))
            tr_chaos = _recorder()
            if use_disagg:
                from accelerate_tpu import DisaggConfig, DisaggServingEngine

                cengine = DisaggServingEngine(
                    res_model, ccfg,
                    disagg=DisaggConfig(n_prefill_lanes=args.lanes),
                    tracing=tr_chaos)
            else:
                cengine = ServingEngine(res_model, ccfg, tracing=tr_chaos)
            cengine.warmup()   # compiles out of TTFT; the tick clock re-zeroes
            cengine.chaos = chaos  # attach after warmup: draws stay replayable
            _, cha_s = replay_trace(cengine, reqs, arrivals=list(arrivals),
                                    max_new_tokens=[int(b) for b in budgets])
            cst = cengine.stats()
            row = {
                "row": "serving_chaos", "seconds": round(cha_s, 3),
                "chaos_seed": args.chaos_seed,
                "useful_tokens": cst["tokens_out"],
                "tokens_per_s": cst["tokens_per_s"],
                "ttft_p50_s": round(cst["ttft_p50_s"], 4),
                "ttft_p95_s": round(cst["ttft_p95_s"], 4),
                "decode_executables": cst["decode_executables"],
                "steady_recompiles": cst["steady_recompiles"],
                "faults": cst["faults"],
                "speculation": cst["speculation"],
            }
            if use_disagg:
                row["degraded"] = cst["disagg"]["degraded"]
                row["healthy_lanes"] = cst["disagg"]["healthy_lanes"]
            if tr_chaos is not None:
                row["tracing"] = _tracing_block(tr_chaos)
                export_tr = tr_chaos
            print(json.dumps(row), flush=True)

        # Publish row: hot-swap a committed, manifest-verified checkpoint
        # into the live engine mid-trace. The publisher redistributes the
        # weights through the reshard executor (bytes priced against the
        # BandwidthTable), opens a canary window over `--canary-fraction`
        # of new admissions, and promotes on the loose SLO gates — the row
        # records swap latency, redistribution bytes, the canary window,
        # and the faults block next to the fault-free serving rows.
        if args.publish:
            from accelerate_tpu import PublishConfig, WeightPublisher
            from accelerate_tpu.fault_tolerance import write_manifest

            pub_root = tempfile.mkdtemp(prefix="gen_bench_publish_")
            pub_ckpt = os.path.join(pub_root, "checkpoint_0")
            os.makedirs(pub_ckpt)
            save_sharded_safetensors(
                {k: np.asarray(v)
                 for k, v in flatten_state_dict(host_params).items()},
                pub_ckpt, max_shard_size=2 * 1024**3,
            )
            write_manifest(pub_ckpt, step=1, world_size=1)

            pengine = ServingEngine(res_model, scfg)
            pengine.warmup()
            pub = WeightPublisher(pengine, PublishConfig(
                checkpoint_dir=pub_root,
                canary_fraction=args.canary_fraction,
                canary_warmup=1, min_cohort=3,
                max_ttft_ratio=100.0, max_tpot_ratio=100.0,
                max_rate_increase=1.0,
            ))
            order = sorted(range(n), key=lambda i: float(arrivals[i]))
            filler = srng.integers(1, cfg.vocab_size, (8,), dtype=np.int32)
            fillers_left = 64
            t0 = time.perf_counter()
            nxt = 0
            decision = None
            while nxt < n or pengine.pending or (
                    decision is None and fillers_left > 0):
                now = time.perf_counter() - t0
                while nxt < n and float(arrivals[order[nxt]]) <= now:
                    i = order[nxt]
                    pengine.submit(reqs[i], max_new_tokens=int(budgets[i]))
                    nxt += 1
                if nxt >= n and decision is None and not pengine.pending \
                        and fillers_left > 0:
                    # The trace drained before the canary window filled:
                    # keep the cohorts fed so the decision lands.
                    pengine.submit(filler, max_new_tokens=8)
                    fillers_left -= 1
                if pengine.pending:
                    pengine.tick()
                    pengine.poll()
                rec = pub.poll()
                if rec is not None and rec["action"] in ("promoted",
                                                         "rolled_back"):
                    decision = rec
            pub_s = time.perf_counter() - t0
            pst = pengine.stats()
            ps = pub.stats()
            published = next((r for r in pub.history
                              if r["action"] == "published"), {})
            print(json.dumps({
                "row": "serving_publish", "seconds": round(pub_s, 3),
                "weights_version": pst["weights_version"],
                "swap_s": published.get("swap_s"),
                "planned_bytes": ps["bytes_planned"],
                "redistributed_bytes": ps["bytes_moved"],
                "predicted_transfer_s": ps["predicted_transfer_s"],
                "transfer_wall_s": ps["transfer_wall_s"],
                "n_devices": published.get("n_devices"),
                "canary_fraction": args.canary_fraction,
                "decision": (decision or {}).get("action"),
                "canary_window": (decision or {}).get("routed"),
                "tokens_per_s": pst["tokens_per_s"],
                "decode_executables": pst["decode_executables"],
                "steady_recompiles": pst["steady_recompiles"],
                "faults": pst["faults"],
            }), flush=True)

        # Autoscale row: the diurnal trace through a disagg engine that
        # starts on HALF the mesh with an AutoscaleController closing the
        # telemetry -> planner -> live-resize loop. The row prices
        # elasticity next to the fixed-topology rows: resize count and
        # decision counters, a per-plateau SLO block (p95 TTFT on the high
        # vs low plateaus), and the executable census (a resize must not
        # recompile the steady state).
        if args.autoscale and len(jax.devices()) < 2:
            print(json.dumps({
                "row": "serving_autoscale", "skipped": "needs >= 2 devices",
            }), flush=True)
        elif args.autoscale:
            from accelerate_tpu import (
                AutoscaleConfig,
                AutoscaleController,
                DisaggConfig,
                DisaggServingEngine,
            )

            pool = jax.devices()
            start = max(2, len(pool) // 2)
            acfg = ServingConfig(n_slots=slots, max_len=t_cap,
                                 max_prefill_chunk=max(16, args.prompt_len),
                                 max_retries=3,
                                 max_idle_ticks=max(100, 4 * t_cap))
            aengine = DisaggServingEngine(
                res_model, acfg,
                disagg=DisaggConfig(n_prefill_lanes=min(args.lanes, start)),
                devices=pool[:start])
            aengine.warmup()
            auto = AutoscaleController(
                aengine,
                AutoscaleConfig(poll_ticks=8, window_min_requests=4,
                                queue_depth_high=3.0, queue_depth_low=0.5,
                                breach_samples=2, cooldown_ticks=40),
                device_pool=pool)
            ids, results = {}, {}
            t0 = time.perf_counter()
            nxt = 0
            while nxt < n or aengine.pending:
                now = time.perf_counter() - t0
                while nxt < n and float(arrivals[nxt]) <= now:
                    ids[nxt] = aengine.submit(reqs[nxt],
                                              max_new_tokens=int(budgets[nxt]))
                    nxt += 1
                if aengine.pending:
                    aengine.tick()
                    auto.poll()
                    for r in aengine.poll():
                        results[r["id"]] = r
            auto_s = time.perf_counter() - t0
            ast = aengine.stats()
            a = auto.stats()

            def _plateau_p95(want_high):
                sel = (phases == 1) if want_high else (phases != 1)
                v = [results[ids[i]]["ttft_s"] for i in range(n)
                     if sel[i] and i in ids
                     and results[ids[i]]["status"] == "ok"
                     and results[ids[i]]["ttft_s"] is not None]
                return (round(float(np.percentile(np.asarray(v), 95)), 4)
                        if v else None)

            print(json.dumps({
                "row": "serving_autoscale", "seconds": round(auto_s, 3),
                "useful_tokens": ast["tokens_out"],
                "tokens_per_s": ast["tokens_per_s"],
                "ttft_p50_s": round(ast["ttft_p50_s"], 4),
                "ttft_p95_s": round(ast["ttft_p95_s"], 4),
                "slo_plateaus": {"ttft_p95_high_s": _plateau_p95(True),
                                 "ttft_p95_low_s": _plateau_p95(False)},
                "autoscale": {k: a[k] for k in (
                    "samples", "decisions", "holds", "grows", "shrinks",
                    "resplits", "dead_device_shrinks", "resizes", "aborts",
                    "flap_damped", "active_devices", "pool_devices")},
                "resize": ast["disagg"]["resize"],
                "decode_executables": ast["decode_executables"],
                "prefill_executables": ast["prefill_executables"],
                "steady_recompiles": ast["steady_recompiles"],
            }), flush=True)

        if args.trace_out and export_tr is not None:
            export_tr.export_chrome_trace(args.trace_out)
            print(json.dumps({"row": "trace_out", "path": args.trace_out,
                              "spans": export_tr.stats()["spans"]}),
                  flush=True)

    # --- Row 3: streamed (blocks in host RAM, layer streaming) -------------
    base = Model(module=module, params=host_params)
    block_map = {"model/layers": "cpu", "": jax.devices()[0]}
    streamed = dispatch_model(base, block_map)
    seq = prompt.copy()
    np.asarray(streamed(seq))  # warm the compile for the prompt shape
    times = []
    for _ in range(args.streamed_tokens):
        t0 = time.perf_counter()
        logits = np.asarray(streamed(seq))
        times.append(time.perf_counter() - t0)
        nxt = logits[:, -1].argmax(-1).astype(np.int32)[:, None]
        seq = np.concatenate([seq, nxt], axis=1)
    print(json.dumps({
        "row": "streamed", "s_per_token": round(float(np.mean(times[1:] or times)), 3),
        "hbm_resident_bytes": int(streamed.hbm_resident_bytes()),
        "tokens": args.streamed_tokens,
    }), flush=True)


if __name__ == "__main__":
    main()
