"""Ablation harness for the bench workload (real chip).

Measures tokens/s/chip for the FSDP Llama train step across remat policies
and loss implementations, to pick bench.py's default configuration.

    python benchmarks/ablate.py [--seq 2048] [--iters 20]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import honor_jax_platforms_env

honor_jax_platforms_env()


def measure(seq, iters, *, remat, remat_policy, fused_loss, batch=None, fp8=False):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import (
        LlamaConfig, LlamaForCausalLM, cross_entropy_loss, fused_cross_entropy_loss,
    )
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)
    import jax.numpy as jnp

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_hidden_layers=16, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=seq, dtype=jnp.bfloat16,
        remat=remat, remat_policy=remat_policy, attention_impl="flash",
        fp8=fp8,
    )
    if batch is None:
        batch = 8 if seq <= 2048 else 2
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1), dtype=np.int32)

    acc = Accelerator(mixed_precision="bf16", fsdp_plugin=FullyShardedDataParallelPlugin())
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adamw(3e-4, weight_decay=0.1))

    if fused_loss:
        def loss_fn(params, b):
            return fused_cross_entropy_loss(cfg, params, b["x"], b["y"])
    else:
        def loss_fn(params, b):
            logits = module.apply({"params": params}, b["x"])
            return cross_entropy_loss(logits, b["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(acc.mesh, PartitionSpec(("dp_replicate", "dp_shard")))
    b = {
        "x": jax.device_put(ids[:, :-1], sharding),
        "y": jax.device_put(ids[:, 1:], sharding),
    }
    state = acc.train_state
    for _ in range(2):
        state, metrics = step(state, b)
        float(np.asarray(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, b)
    loss = float(np.asarray(metrics["loss"]))
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(loss), loss
    return batch * seq / dt / len(jax.devices()), loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--variants", type=str, default="")
    args = ap.parse_args()

    variants = {
        "remat-flash+naive-ce": dict(remat=True, remat_policy="flash", fused_loss=False),
        "remat-flash+fused-ce": dict(remat=True, remat_policy="flash", fused_loss=True),
        "remat-dots+naive-ce": dict(remat=True, remat_policy="dots", fused_loss=False),
        "remat-dots+fused-ce": dict(remat=True, remat_policy="dots", fused_loss=True),
        "no-remat+fused-ce": dict(remat=False, remat_policy="flash", fused_loss=True),
        "no-remat+naive-ce": dict(remat=False, remat_policy="flash", fused_loss=False),
        # fp8 (QDQ e4m3/e5m2 HYBRID) vs its bf16 twin — the reference's
        # headline fp8 claim is +25% tok/s at loss parity
        # (examples/torch_native_parallelism/README.md); this row either
        # reproduces that on TPU or documents that the XLA fp8 rewriter
        # does not pay off on this generation (docs/performance.md).
        "fp8+remat-dots+naive-ce": dict(remat=True, remat_policy="dots", fused_loss=False, fp8=True),
    }
    if args.variants:
        keep = args.variants.split(",")
        variants = {k: v for k, v in variants.items() if k in keep}
    for name, kw in variants.items():
        # flush per row (streaming-evidence rule, round-3 postmortem): a
        # driver timeout mid-sweep must keep every finished variant's number.
        try:
            tok, loss = measure(args.seq, args.iters, **kw)
            print(f"{name:28s} {tok:10.1f} tok/s/chip   loss {loss:.4f}", flush=True)
        except Exception as e:
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
