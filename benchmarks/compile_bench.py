"""Compile-time benchmark: scan-over-layers vs unrolled blocks.

The reference's regional-compilation headline is 5-9x faster compiles at
inference parity (/root/reference/benchmarks/torch.compile/README.md —
Llama-3.1-8B: 2.9 s regional vs 20.4 s full). The TPU-native analog is
``scan_layers=True``: ``nn.scan`` compiles ONE block and iterates it, so
compile time is O(1) in depth instead of O(L). This bench measures wall-time
to trace+compile a forward step both ways at two depths and prints one JSON
row per configuration (streamed, driver-kill-proof).

    python benchmarks/compile_bench.py [--layers 18 --hidden 2048]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(layers: int, hidden: int, scan: bool, seq: int = 256) -> dict:
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=hidden * 11 // 4,
        num_hidden_layers=layers, num_attention_heads=max(1, hidden // 128),
        num_key_value_heads=max(1, hidden // 128), max_position_embeddings=seq,
        dtype=jnp.bfloat16, scan_layers=scan,
    )
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, seq), dtype=np.int32))
    params = jax.eval_shape(lambda k: module.init(k, ids), jax.random.key(0))["params"]

    def fwd(p, x):
        return module.apply({"params": p}, x)

    t0 = time.perf_counter()
    lowered = jax.jit(fwd).lower(params, ids)
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    del compiled
    return {
        "row": "compile", "scan_layers": scan, "layers": layers,
        "hidden": hidden, "seconds": round(dt, 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=18)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--seq", type=int, default=256)
    args = ap.parse_args()

    import jax

    from accelerate_tpu.utils.environment import honor_jax_platforms_env

    honor_jax_platforms_env()
    print(json.dumps({"row": "start", "platform": jax.devices()[0].platform}), flush=True)

    rows = []
    for scan in (True, False):
        rows.append(measure(args.layers, args.hidden, scan, args.seq))
        print(json.dumps(rows[-1]), flush=True)
    speedup = rows[1]["seconds"] / max(rows[0]["seconds"], 1e-9)
    print(json.dumps({
        "row": "summary", "layers": args.layers,
        "scan_compile_s": rows[0]["seconds"], "unrolled_compile_s": rows[1]["seconds"],
        "speedup": round(speedup, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
