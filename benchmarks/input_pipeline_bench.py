"""Input-pipeline benchmark: does the loader keep up with the training step?

Answers two questions with numbers (VERDICT r4 'what's weak' #5):

1. **Overlap** — with native C++ collation (native/host_runtime.cpp) + the
   prefetch thread, what fraction of a bench-shaped step time does the loader
   steal? The reference's MpDeviceLoader (data_loader.py:669-719) exists for
   exactly this; here the claim is measured: added wall-clock per step vs a
   pure-compute loop, at the 1B@2048 target step time (~80 ms) and a tighter
   ~25 ms decode-shaped step.

2. **Dispatch-mode cost** — DataLoaderDispatcher pays a per-batch
   ``broadcast_object_list`` (rank 0 reads + pickles the full batch). How
   many ms/batch vs shard mode, same data? (reference: data_loader.py:804-944)

Host-side only — runs anywhere, no TPU needed. Emits one JSON line per
measurement. The dispatch measurement self-launches a 2-process CPU gang.
"""

import json
import os
import sys
import time

import numpy as np

# Host-side benchmark: never touch an accelerator backend (a dead axon relay
# would hang jax.devices() inside PartialState). The env var alone is not
# enough under the axon site hook — re-assert through jax.config.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

SEQ = 2048
BATCH = 8
N_BATCHES = 60


def _dataset(n_samples: int):
    rng = np.random.default_rng(0)
    return [
        {"input_ids": rng.integers(0, 32000, SEQ).astype(np.int32),
         "labels": rng.integers(0, 32000, SEQ).astype(np.int32)}
        for _ in range(n_samples)
    ]


def _collate(samples):
    from accelerate_tpu.native import stack_items

    return {
        k: stack_items([s[k] for s in samples]) for k in samples[0]
    }


def _loader(prefetch_size: int, force_python: bool):
    import torch.utils.data as tud

    from accelerate_tpu.data_loader import prepare_data_loader

    if force_python:
        os.environ["ACCELERATE_DISABLE_NATIVE"] = "1"
    else:
        os.environ.pop("ACCELERATE_DISABLE_NATIVE", None)
    ds = _dataset(BATCH * N_BATCHES)
    dl = tud.DataLoader(ds, batch_size=BATCH, collate_fn=_collate, shuffle=False)
    return prepare_data_loader(dl, put_on_device=False, prefetch_size=prefetch_size)


def bench_overlap(step_ms: float, prefetch_size: int, force_python: bool) -> dict:
    """Walk the loader with a simulated device-bound step (time.sleep releases
    the GIL exactly like a dispatched device computation) and report the
    loader's added wall-clock per step."""
    dl = _loader(prefetch_size, force_python)
    it = iter(dl)
    next(it)  # warm: thread started, first batch buffered
    t0 = time.perf_counter()
    n = 0
    for _ in it:
        time.sleep(step_ms / 1e3)
        n += 1
    wall = time.perf_counter() - t0
    per_step_ms = wall / n * 1e3
    idle_ms = per_step_ms - step_ms
    return {
        "metric": "input_pipeline_overlap",
        "step_ms": step_ms,
        "prefetch": prefetch_size,
        "native_collation": not force_python,
        "per_step_ms": round(per_step_ms, 3),
        "loader_added_ms": round(idle_ms, 3),
        "loader_idle_frac": round(max(0.0, idle_ms) / step_ms, 4),
        "n": n,
    }


def bench_dispatch_vs_shard() -> None:
    """2-process gang: ms/batch for dispatch mode (per-batch object
    broadcast) vs shard mode (each rank reads its own shard)."""
    import subprocess

    from accelerate_tpu.test_utils import get_launch_command

    cmd = get_launch_command(num_processes=2, virtual_devices=2) + [
        __file__, "--gang-child"
    ]
    r = subprocess.run(
        cmd, env={**os.environ, "PYTHONPATH": os.getcwd()},
        capture_output=True, text=True, timeout=600,
    )
    if r.returncode != 0:
        print(json.dumps({"metric": "dispatch_vs_shard", "error": r.stderr[-1500:]}))
        return
    for line in r.stdout.splitlines():
        if line.startswith("{"):
            print(line)


def _gang_child() -> None:
    import torch.utils.data as tud

    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.state import PartialState

    state = PartialState()
    ds = _dataset(BATCH * N_BATCHES)
    rows = {}
    for mode, group in (("shard", 1), ("dispatch_g1", 1), ("dispatch_g8", 8)):
        dl = prepare_data_loader(
            tud.DataLoader(ds, batch_size=BATCH, collate_fn=_collate, shuffle=False),
            put_on_device=False,
            dispatch_batches=mode.startswith("dispatch"),
            dispatch_group_size=group,
        )
        it = iter(dl)
        next(it)
        t0 = time.perf_counter()
        n = sum(1 for _ in it)
        rows[mode] = (time.perf_counter() - t0) / n * 1e3
    if state.is_main_process:
        print(json.dumps({
            "metric": "dispatch_vs_shard",
            "shard_ms_per_batch": round(rows["shard"], 3),
            "dispatch_group1_ms_per_batch": round(rows["dispatch_g1"], 3),
            "dispatch_group8_ms_per_batch": round(rows["dispatch_g8"], 3),
            "group8_overhead_ms": round(rows["dispatch_g8"] - rows["shard"], 3),
            "batch_bytes": int(BATCH * SEQ * 4 * 2),
        }), flush=True)


def main() -> None:
    if "--gang-child" in sys.argv:
        _gang_child()
        return
    for step_ms in (80.0, 25.0):
        for prefetch, force_py in ((2, False), (2, True), (0, False)):
            print(json.dumps(bench_overlap(step_ms, prefetch, force_py)), flush=True)
    bench_dispatch_vs_shard()


if __name__ == "__main__":
    main()
